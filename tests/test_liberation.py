"""Liberation-family codecs: liberation, blaum_roth, liber8tion.

Mirrors the reference's typed-test sweep (TestErasureCodeJerasure.cc):
exhaustive 1- and 2-erasure reconstruction with content verification
across the parameter space, geometry validation, packet-layout
invariants, and cross-language bit-exactness (numpy == jax == native).
"""

from __future__ import annotations

import itertools
import shutil

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.errors import ErasureCodeError
from ceph_tpu.models.liberation import binary_invert

PARAMS = [
    ("liberation", 2, 3), ("liberation", 3, 5), ("liberation", 5, 7),
    ("liberation", 7, 7), ("liberation", 11, 11),
    ("blaum_roth", 2, 4), ("blaum_roth", 4, 6), ("blaum_roth", 6, 6),
    ("blaum_roth", 10, 10),
    ("liber8tion", 2, 8), ("liber8tion", 5, 8), ("liber8tion", 8, 8),
]


def _codec(plugin, technique, k, w, packetsize=8):
    return registry.factory(plugin, {
        "technique": technique, "k": str(k), "w": str(w),
        "packetsize": str(packetsize)})


@pytest.mark.parametrize("technique,k,w", PARAMS)
class TestEncodeDecode:
    def test_all_erasure_patterns(self, technique, k, w):
        c = _codec("jerasure", technique, k, w)
        n = c.k + c.m
        assert c.m == 2
        rng = np.random.default_rng(7)
        data = bytes(rng.integers(0, 256, 2000, dtype=np.uint8))
        enc = c.encode(set(range(n)), data)
        # systematic: data chunks concatenate back to the input
        flat = b"".join(bytes(enc[i]) for i in range(k))
        assert flat[: len(data)] == data
        for r in (1, 2):
            for lost in itertools.combinations(range(n), r):
                avail = {i: enc[i] for i in range(n) if i not in lost}
                dec = c.decode(set(lost), avail)
                for i in lost:
                    assert bytes(dec[i]) == bytes(enc[i])

    def test_three_erasures_fail(self, technique, k, w):
        c = _codec("jerasure", technique, k, w)
        n = c.k + c.m
        data = b"x" * 500
        enc = c.encode(set(range(n)), data)
        avail = {i: enc[i] for i in range(n - 3)}
        assert len(avail) < k  # m=2 family: n-3 == k-1 survivors, always short
        with pytest.raises(ErasureCodeError):
            c.decode(set(range(n - 3, n)), avail)

    def test_jax_matches_numpy(self, technique, k, w):
        cpu = _codec("jerasure", technique, k, w)
        tpu = _codec("jax_tpu", technique, k, w)
        rng = np.random.default_rng(3)
        data = bytes(rng.integers(0, 256, 1000, dtype=np.uint8))
        n = cpu.k + cpu.m
        e_cpu = cpu.encode(set(range(n)), data)
        e_tpu = tpu.encode(set(range(n)), data)
        for i in range(n):
            assert bytes(e_cpu[i]) == bytes(e_tpu[i])


class TestGeometryValidation:
    def test_liberation_w_must_be_prime(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "liberation", 2, 4)

    def test_liberation_k_le_w(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "liberation", 8, 7)

    def test_blaum_roth_w_plus_1_prime(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "blaum_roth", 2, 5)

    def test_liber8tion_w_is_8(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "liber8tion", 2, 7)

    def test_liber8tion_k_le_8(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "liber8tion", 9, 8)

    def test_m_forced_to_2(self):
        c = registry.factory("jerasure", {
            "technique": "liberation", "k": "3", "w": "5",
            "packetsize": "8"})
        assert c.m == 2 and c.get_profile()["m"] == "2"

    def test_packetsize_multiple_of_8(self):
        with pytest.raises(ErasureCodeError):
            _codec("jerasure", "liberation", 2, 5, packetsize=5)


class TestBinaryInvert:
    def test_roundtrip(self):
        rng = np.random.default_rng(11)
        for n in (1, 4, 16, 40):
            while True:
                a = rng.integers(0, 2, (n, n), dtype=np.uint8)
                try:
                    inv = binary_invert(a)
                    break
                except ValueError:
                    continue
            assert ((a.astype(np.uint16) @ inv.astype(np.uint16)) % 2
                    == np.eye(n)).all()

    def test_singular_raises(self):
        with pytest.raises(ValueError):
            binary_invert(np.zeros((3, 3), dtype=np.uint8))


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
class TestNativeParity:
    @pytest.mark.parametrize("technique,k,w", PARAMS[:2] + PARAMS[5:7]
                             + PARAMS[-2:])
    def test_native_bit_exact(self, technique, k, w):
        from ceph_tpu import native
        native.build()
        prof = {"technique": technique, "k": str(k), "w": str(w),
                "packetsize": "8"}
        nat = native.NativeCodec("jerasure", dict(prof))
        py = registry.factory("jerasure", dict(prof))
        rng = np.random.default_rng(5)
        data = bytes(rng.integers(0, 256, 1500, dtype=np.uint8))
        n = nat.k + nat.m
        e_nat = nat.encode(data)
        e_py = py.encode(set(range(n)), data)
        for i in range(n):
            assert e_nat[i] == bytes(e_py[i])
        for lost in itertools.combinations(range(n), 2):
            avail = {i: e_nat[i] for i in range(n) if i not in lost}
            dec = nat.decode(avail, want=list(lost))
            for i in lost:
                assert dec[i] == e_nat[i]
