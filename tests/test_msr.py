"""Product-matrix MSR regenerating codec (repair-bandwidth-optimal
recovery, ROADMAP direction C).

Three layers under test: the codec construction itself (systematic
roundtrip, beta-fraction repair bit-identical to the host oracle, the
jax/numpy backend parity), the dispatcher/mesh repair legs, and the
cluster repair path (helper fractions over sub-ops, fallback ordering,
the no-double-count accounting contract).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.models.base import ErasureCodeError
from ceph_tpu.osd import ec_util
from .cluster_util import MiniCluster, wait_until

K, M = 4, 3          # alpha = 3, d = 6, n = 7
FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02,
        # the cluster tests target the HELPER-FRACTION rebuild, not
        # the resident fast path
        "osd_hbm_tier_enable": False}


def _profile(k=K, m=M):
    return {"technique": "msr", "k": str(k), "m": str(m), "w": "8"}


@pytest.fixture(scope="module")
def codec():
    return registry.factory("msr_tpu", _profile())


@pytest.fixture(scope="module")
def host_codec():
    return registry.factory("msr", _profile())


def _stripes(codec, n=None, seed=3, stripes=4):
    n = n or codec.get_chunk_size(1 << 16)
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=(stripes, codec.k, n),
                        dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(data), dtype=np.uint8)
    rows = {codec.chunk_index(i): data[:, i] for i in range(codec.k)}
    rows.update({codec.chunk_index(codec.k + j): parity[:, j]
                 for j in range(codec.m)})
    return data, rows


class TestCodec:
    def test_registry_and_geometry(self, codec):
        assert codec.technique == "msr"
        assert codec.alpha == K - 1
        assert codec.d == 2 * (K - 1)
        assert codec.supports_repair()
        assert codec.repair_fraction() == pytest.approx(1 / (K - 1))
        assert codec.repair_helper_count() == codec.d
        # alignment guarantees every chunk splits into alpha sub-rows
        assert codec.get_chunk_size(1 << 16) % codec.alpha == 0

    def test_profile_validation(self):
        with pytest.raises(ErasureCodeError):
            registry.factory("msr", {"technique": "msr", "k": "4",
                                     "m": "2", "w": "8"})  # m < k-1
        with pytest.raises(ErasureCodeError):
            registry.factory("msr", {"technique": "msr", "k": "2",
                                     "m": "2", "w": "8"})  # k < 3
        with pytest.raises(ErasureCodeError):
            registry.factory("msr", {"technique": "msr", "k": "4",
                                     "m": "3", "w": "16"})  # w != 8

    def test_decode_roundtrip_any_k_survivors(self, codec):
        import itertools
        data, rows = _stripes(codec)
        n = codec.get_chunk_count()
        logical = {i: rows[codec.chunk_index(i)] for i in range(n)}
        for avail in itertools.islice(
                itertools.combinations(range(n), codec.k), 6):
            chunks = np.stack([logical[i] for i in avail], axis=1)
            out = np.asarray(codec.decode_batch(avail, chunks),
                             dtype=np.uint8)
            for i in range(n):
                assert np.array_equal(out[:, i], logical[i]), \
                    (avail, i)

    def test_repair_bit_identical_to_oracle(self, codec):
        data, rows = _stripes(codec)
        # one data target and one parity target
        for target in (codec.chunk_index(1),
                       codec.chunk_index(codec.k + 1)):
            helpers = tuple(sorted(codec.minimum_to_repair(
                target, set(rows) - {target})))
            assert len(helpers) == codec.d
            fracs = np.stack(
                [np.asarray(codec.repair_fraction_batch(
                    target, rows[h]), dtype=np.uint8)
                 for h in helpers], axis=1)
            # each fraction is 1/alpha of the chunk
            assert fracs.shape[2] * codec.alpha == rows[target].shape[1]
            rebuilt = np.asarray(codec.repair_combine_batch(
                target, helpers, fracs), dtype=np.uint8)
            assert np.array_equal(rebuilt, rows[target])
            for s in range(data.shape[0]):
                oracle = codec.repair_oracle(
                    target, helpers, {h: rows[h][s] for h in helpers})
                assert np.array_equal(rebuilt[s], oracle)

    def test_jax_numpy_backend_parity(self, codec, host_codec):
        data, rows = _stripes(codec)
        target = codec.chunk_index(0)
        helpers = tuple(sorted(codec.minimum_to_repair(
            target, set(rows) - {target})))
        for h in helpers[:2]:
            a = np.asarray(codec.repair_fraction_batch(target, rows[h]))
            b = np.asarray(host_codec.repair_fraction_batch(
                target, rows[h]))
            assert np.array_equal(a, b)

    def test_minimum_to_repair_needs_d(self, codec):
        avail = set(range(codec.d))      # d shards, one is the target
        with pytest.raises(ErasureCodeError):
            codec.minimum_to_repair(0, avail)
        avail.add(codec.d)
        assert len(codec.minimum_to_repair(0, avail)) == codec.d

    def test_traffic_is_below_full_decode(self, codec):
        chunk = codec.get_chunk_size(1 << 16)
        moved = codec.d * codec.repair_sub_size(chunk)
        assert moved < codec.k * chunk


class TestRepairLegs:
    def test_dispatcher_repair_matches_host(self, codec):
        from ceph_tpu.osd.tpu_dispatch import TpuDispatcher
        data, rows = _stripes(codec)
        target = codec.chunk_index(2)
        helpers = tuple(sorted(codec.minimum_to_repair(
            target, set(rows) - {target})))
        disp = TpuDispatcher(max_delay=0.002)
        try:
            fracs = np.stack(
                [np.asarray(disp.repair_fraction(codec, target,
                                                 rows[h]))
                 for h in helpers], axis=1)
            rebuilt = np.asarray(disp.repair_combine(
                codec, target, helpers, fracs))
        finally:
            disp.shutdown()
        assert np.array_equal(rebuilt, rows[target])

    def test_mesh_repair_sharded_and_checksum(self, codec):
        from ceph_tpu.parallel.mesh import MeshChecksumError, \
            make_mesh, repair_sharded
        data, rows = _stripes(codec, stripes=8)
        target = codec.chunk_index(1)
        helpers = tuple(sorted(codec.minimum_to_repair(
            target, set(rows) - {target})))
        fracs = np.stack(
            [np.asarray(codec.repair_fraction_batch(target, rows[h]),
                        dtype=np.uint8) for h in helpers], axis=1)
        m = make_mesh(8)
        out = repair_sharded(codec, target, helpers, fracs, mesh=m)
        assert np.array_equal(
            out, rows[target].reshape(rows[target].shape[0], -1))
        expected = int(fracs.astype(np.uint64).sum()) % (1 << 32)
        fracs[2, 1, 5] ^= 0xFF
        with pytest.raises(MeshChecksumError):
            repair_sharded(codec, target, helpers, fracs, mesh=m,
                           expected_sum=expected)

    def test_ec_util_repair_roundtrip(self, codec):
        sinfo = ec_util.StripeInfo(codec.get_data_chunk_count(),
                                   codec.get_chunk_size(1 << 16) *
                                   codec.get_data_chunk_count())
        data, rows = _stripes(codec, n=sinfo.chunk_size)
        target = codec.chunk_index(0)
        helpers = tuple(sorted(codec.minimum_to_repair(
            target, set(rows) - {target})))
        fractions = {
            h: ec_util.repair_fraction(
                sinfo, codec, target, rows[h].reshape(-1).tobytes())
            for h in helpers}
        sub = codec.repair_sub_size(sinfo.chunk_size)
        assert all(len(v) == data.shape[0] * sub
                   for v in fractions.values())
        out = ec_util.repair_combine(sinfo, codec, target, fractions)
        assert out == rows[target].reshape(-1).tobytes()
        mesh_out = ec_util.repair_cross_chip(sinfo, codec, target,
                                             fractions)
        assert mesh_out == out

    def test_recover_cross_chip_gated_for_sub_symbol_codecs(self,
                                                            codec):
        # whole-chunk mesh decode reshapes chunk rows; for alpha > 1
        # that would shred the sub-symbol layout — must decline
        sinfo = ec_util.StripeInfo(codec.get_data_chunk_count(),
                                   codec.get_chunk_size(1 << 16) *
                                   codec.get_data_chunk_count())
        data, rows = _stripes(codec, n=sinfo.chunk_size)
        shard_data = {codec.chunk_index(i):
                      rows[codec.chunk_index(i)].reshape(-1).tobytes()
                      for i in range(codec.k)}
        assert ec_util.recover_cross_chip(
            sinfo, codec, shard_data, codec.chunk_index(codec.k)) \
            is None


def _ec_target(cluster, client, pool_name, oid):
    m = client.osdmap
    pool_id = client.pool_id(pool_name)
    pgid = m.pools[pool_id].raw_pg_to_pg(m.object_to_pg(pool_id, oid))
    _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
    return pgid, acting, primary


def _repair_counters(cluster):
    out = {"read": 0, "shipped": 0, "saved": 0}
    for osd in cluster.osds.values():
        for lane in out:
            out[lane] += osd.perf.get("l_osd_repair_bytes_" + lane)
    return out


def _recover(pg, oid, shard, timeout=30.0):
    done = threading.Event()
    got: list = [None]

    def on_done(data):
        got[0] = data
        done.set()

    pg.backend.recover_object(oid, shard, on_done)
    assert done.wait(timeout), "recover_object never completed"
    return got[0]


class TestClusterRepair:
    def test_beta_fraction_repair_heals_bitrot(self):
        """The full loop at cluster level: bit-rot one shard, scrub
        repair rebuilds it through d helper fractions, the counters
        show fraction traffic (shipped = read/alpha, saved > 0)."""
        cluster = MiniCluster(num_mons=1, num_osds=5,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "msrheal",
                                   {"plugin": "msr", "technique": "msr",
                                    "k": "3", "m": "2"}, pg_num=4)
            ioctx = client.open_ioctx("msrheal")
            payload = bytes(np.random.default_rng(5).integers(
                0, 256, 40000, dtype=np.uint8))
            ioctx.write_full("mobj", payload)
            pgid, acting, primary = _ec_target(cluster, client,
                                               "msrheal", "mobj")
            victim = cluster.osds[acting[1]]
            cid = ("pg", str(pgid), 1)
            good = victim.store.read(cid, "mobj")
            victim.store.faults.mark_bitrot(cid, "mobj")

            osd = cluster.osds[primary]
            pg = osd.pgs[pgid]
            assert osd.scrub_pg(pgid, deep=True, repair=True)
            assert wait_until(
                lambda: pg.scrub_stats.get("state") == "clean"
                and pg.scrub_stats.get("repaired", 0) >= 1, 30), \
                pg.scrub_stats
            assert wait_until(
                lambda: victim.store.read(cid, "mobj") == good, 15)
            assert ioctx.read("mobj") == payload

            ctr = _repair_counters(cluster)
            alpha = 2                      # k=3
            assert ctr["shipped"] > 0
            assert ctr["read"] == ctr["shipped"] * alpha
            assert ctr["saved"] > 0
        finally:
            cluster.stop()

    def test_helper_eio_substitutes_without_double_count(self):
        """A helper whose store EIOs mid-repair is replaced by an
        untried survivor; repair bytes are counted once per SUCCESSFUL
        fraction only — the failed helper inflates nothing."""
        cluster = MiniCluster(num_mons=1, num_osds=6,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            # k=3, m=3: n=6, d=4, and 5 survivors leave one spare
            cluster.create_ec_pool(client, "msreio",
                                   {"plugin": "msr", "technique": "msr",
                                    "k": "3", "m": "3"}, pg_num=4)
            ioctx = client.open_ioctx("msreio")
            payload = bytes(np.random.default_rng(6).integers(
                0, 256, 30000, dtype=np.uint8))
            ioctx.write_full("eobj", payload)
            pgid, acting, primary = _ec_target(cluster, client,
                                               "msreio", "eobj")
            target_shard = 5               # rebuild the last shard
            # EIO the LOWEST survivor shard: minimum_to_repair picks
            # the d lowest, so this helper is guaranteed to be asked
            bad_shard = 0
            bad = cluster.osds[acting[bad_shard]]
            bad_cid = ("pg", str(pgid), bad_shard)
            bad.store.faults.mark_eio(bad_cid, "eobj")

            osd = cluster.osds[primary]
            pg = osd.pgs[pgid]
            good = cluster.osds[acting[target_shard]].store.read(
                ("pg", str(pgid), target_shard), "eobj")
            before = _repair_counters(cluster)
            out = _recover(pg, "eobj", target_shard)
            assert out == good, "substituted repair diverged"

            # the reply-path self-heal rewrites the EIO'd shard
            # asynchronously (pg.repair_shard); wait for the repair
            # machinery to go quiet before auditing the counters
            assert wait_until(
                lambda: all(not o.pgs[pgid].backend.inflight_repairs
                            for o in cluster.osds.values()
                            if pgid in o.pgs), 20)
            ctr = _repair_counters(cluster)
            d, alpha = 4, 2
            chunk_total = len(good)
            sub = chunk_total // alpha
            reads = (ctr["read"] - before["read"]) // chunk_total
            ships = (ctr["shipped"] - before["shipped"]) // sub
            # every successful fraction counted EXACTLY once in both
            # lanes (the EIO'd helper contributed zero), and at least
            # one full d-helper round completed
            assert reads == ships >= d, ctr
            assert (ctr["read"] - before["read"]) % chunk_total == 0
            assert (ctr["shipped"] - before["shipped"]) % sub == 0
        finally:
            cluster.stop()

    def test_fewer_than_d_helpers_falls_back_to_survivor_decode(self):
        """k=3, m=2: n=5 and d=4, so losing TWO OSDs leaves only 3
        survivors — below the repair degree (losing one leaves exactly
        d, which repair handles). recover_object must degrade to the
        classic full-survivor decode (shipping no fractions) yet still
        rebuild the lost shard exactly."""
        cluster = MiniCluster(num_mons=1, num_osds=5,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "msrfall",
                                   {"plugin": "msr", "technique": "msr",
                                    "k": "3", "m": "2"}, pg_num=4)
            ioctx = client.open_ioctx("msrfall")
            payload = bytes(np.random.default_rng(8).integers(
                0, 256, 30000, dtype=np.uint8))
            ioctx.write_full("fobj", payload)
            pgid, acting, primary = _ec_target(cluster, client,
                                               "msrfall", "fobj")
            down = [s for s in range(5) if acting[s] != primary][:2]
            down_osds = [acting[s] for s in down]
            target_shard = down[0]
            good = cluster.osds[down_osds[0]].store.read(
                ("pg", str(pgid), target_shard), "fobj")
            before = _repair_counters(cluster)
            for o in down_osds:
                cluster.stop_osd(o)
            assert wait_until(
                lambda: all(not cluster.leader().osdmon.osdmap
                            .is_up(o) for o in down_osds), 30)
            osd = cluster.osds[primary]

            def peered():
                pg = osd.pgs.get(pgid)
                return pg is not None and not (
                    set(down_osds) &
                    set(pg.acting_shards().values()))
            assert wait_until(peered, 30)
            pg = osd.pgs[pgid]
            out = _recover(pg, "fobj", target_shard)
            assert out == good, "survivor-decode fallback diverged"
            ctr = _repair_counters(cluster)
            assert ctr["shipped"] == before["shipped"], \
                "fractions shipped despite < d live helpers"
        finally:
            cluster.stop()

    def test_repair_messages_roundtrip_encoding(self):
        """The new repair sub-op envelopes survive the wire codec (the
        corpus keeps the frozen bytes; this guards live roundtrip
        including payload fields)."""
        from ceph_tpu import encoding
        from ceph_tpu.msg.message import (MOSDECSubOpRepairRead,
                                          MOSDECSubOpRepairReadReply)
        from ceph_tpu.osd.osd_map import PGID
        req = MOSDECSubOpRepairRead(
            pgid=PGID(3, 7), shard=2, from_osd=4, tid=99, oid="obj-x",
            target_shard=5, chunk_len=12288, map_epoch=11,
            trace_id=123, parent_span=7)
        blob = encoding.encode_any(req)
        back = encoding.decode_any(blob)
        assert back.pgid == req.pgid and back.shard == 2
        assert back.target_shard == 5 and back.chunk_len == 12288
        rep = MOSDECSubOpRepairReadReply(
            pgid=PGID(3, 7), shard=2, from_osd=1, tid=99, oid="obj-x",
            fraction=b"\x01\x02\x03\x04", error=0)
        back = encoding.decode_any(encoding.encode_any(rep))
        assert back.fraction == b"\x01\x02\x03\x04"
        assert back.error == 0
