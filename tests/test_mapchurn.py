"""Map-churn survival (ISSUE 19): incremental OSDMap pipeline,
trim/full-map fallback, peering storm control, huge-map balancer
convergence and the map-churn thrash riders.

Mirrors the reference's OSDMap/MOSDMap machinery
(OSDMonitor::build_incremental + send_incremental, osd_map_message_max
batching, mon_min_osdmap_epochs trimming, OSD::osd_map_max_advance) at
in-process scale: a subscriber behind the trim floor gets exactly one
full map, everyone else catches up through bounded incremental frames,
and a daemon applies at most osd_map_max_advance epochs per tick.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from ceph_tpu import encoding
from ceph_tpu.osd.osd_map import (CRUSH_ITEM_NONE, Incremental, OSDMap,
                                  OSDMapMapping, PGID)
from ceph_tpu.tools import osdmaptool

from .cluster_util import MiniCluster, wait_until
from .thrasher import Thrasher

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


def _churn_epochs(client, cluster, n: int, seed: int = 0) -> None:
    """Drive at least n committed osdmap epochs via reweight churn
    (every accepted reweight is one epoch)."""
    rng = random.Random(seed)
    start = cluster.osdmap_epoch()
    osds = sorted(cluster.osds)
    i = 0
    while cluster.osdmap_epoch() < start + n:
        osd = osds[i % len(osds)]
        w = rng.uniform(0.7, 0.99)
        # reweights pend until the next paxos propose: capture the
        # target epoch BEFORE the command (the pend can commit before
        # mon_command returns) and wait for the commit so each round
        # lands its own epoch instead of merging
        want = cluster.osdmap_epoch() + 1
        res, outs, _ = client.mon_command(
            {"prefix": "osd reweight", "id": osd, "weight": w})
        assert res == 0, outs
        assert wait_until(
            lambda: cluster.osdmap_epoch() >= want, timeout=30), \
            "reweight never committed (epoch %d)" \
            % cluster.osdmap_epoch()
        i += 1
        assert i < n * 8, "churn stalled at epoch %d (want %d)" \
            % (cluster.osdmap_epoch(), start + n)


# ---------------------------------------------------------------------------
# property test: incremental fold == mon full map, bit-equal encoded


def _random_inc(rng: random.Random, m: OSDMap) -> Incremental:
    """One random churn inc drawn from the steady-state classes:
    up/down flaps, reweights, pg_temp/primary_temp overlays, upmap
    edits."""
    inc = Incremental(m.epoch + 1)
    pool = m.pools[0]
    roll = rng.random()
    pgid = PGID(0, rng.randrange(pool.pg_num))
    if roll < 0.25:
        osd = rng.randrange(m.max_osd)
        if m.is_up(osd):
            inc.new_down.append(osd)
        else:
            inc.new_up[osd] = ("127.0.0.1", 6800 + osd)
    elif roll < 0.45:
        inc.new_weight[rng.randrange(m.max_osd)] = \
            rng.choice([0x8000, 0xc000, 0xffff, 0x10000])
    elif roll < 0.65:
        if pgid in m.pg_temp and rng.random() < 0.5:
            inc.new_pg_temp[pgid] = []          # clear
        else:
            inc.new_pg_temp[pgid] = sorted(
                rng.sample(range(m.max_osd), pool.size))
    elif roll < 0.8:
        if pgid in m.primary_temp and rng.random() < 0.5:
            inc.new_primary_temp[pgid] = -1     # clear
        else:
            inc.new_primary_temp[pgid] = rng.randrange(m.max_osd)
    else:
        if pgid in m.pg_upmap_items and rng.random() < 0.5:
            inc.old_pg_upmap_items.append(pgid)
        else:
            a, b = rng.sample(range(m.max_osd), 2)
            inc.new_pg_upmap_items[pgid] = [(a, b)]
    return inc


class TestIncrementalProperty:
    def test_random_inc_folds_bit_equal_to_full_map(self):
        """Fold 60 random Incrementals through a wire round-trip
        (encode/decode each inc) into a follower map; at EVERY epoch
        the follower must encode bit-identical to the authoritative
        map.  Mid-sequence, simulate trim-floor fallbacks: replace the
        follower with a decoded full-map snapshot and keep folding."""
        rng = random.Random(1234)
        mon = osdmaptool.create_simple(12, pg_num=64, pool_size=3,
                                       hosts=6)
        follower = encoding.decode_any(encoding.encode_any(mon))
        assert encoding.encode_any(follower) == \
            encoding.encode_any(mon)
        for step in range(60):
            inc = _random_inc(rng, mon)
            mon.apply_incremental(inc)
            wire_inc = encoding.decode_any(encoding.encode_any(inc))
            follower.apply_incremental(wire_inc)
            assert encoding.encode_any(follower) == \
                encoding.encode_any(mon), \
                "divergence at epoch %d (step %d)" % (mon.epoch, step)
            if step % 17 == 16:
                # trim-floor fallback boundary: the follower is thrown
                # away and re-seeded from one full wire map
                follower = encoding.decode_any(
                    encoding.encode_any(mon))
                assert follower.epoch == mon.epoch
                assert encoding.encode_any(follower) == \
                    encoding.encode_any(mon)

    def test_mapping_incremental_matches_full_rebuild(self):
        """OSDMapMapping.apply_incremental on overlay-only incs must
        land on exactly the state a full rebuild computes — while
        touching only the affected PGs."""
        rng = random.Random(77)
        m = osdmaptool.create_simple(16, pg_num=128, pool_size=3,
                                     hosts=8)
        mapping = OSDMapMapping()
        mapping.update(m, batched=False)
        pool = m.pools[0]
        total = pool.pg_num
        saw_incremental = False
        for step in range(30):
            inc = Incremental(m.epoch + 1)
            pgid = PGID(0, rng.randrange(pool.pg_num))
            roll = rng.random()
            if roll < 0.3:
                inc.new_pg_temp[pgid] = sorted(
                    rng.sample(range(m.max_osd), pool.size))
            elif roll < 0.5:
                inc.new_primary_temp[pgid] = rng.randrange(m.max_osd)
            elif roll < 0.7:
                a, b = rng.sample(range(m.max_osd), 2)
                inc.new_pg_upmap_items[pgid] = [(a, b)]
            elif roll < 0.85:
                up = [o for o in range(m.max_osd) if m.is_up(o)]
                if len(up) <= 3:
                    continue
                inc.new_down.append(rng.choice(up))
            else:
                if not m.pg_upmap_items:
                    continue
                inc.old_pg_upmap_items.append(
                    rng.choice(sorted(m.pg_upmap_items, key=str)))
            m.apply_incremental(inc)
            info = mapping.apply_incremental(m, inc, batched=False)
            assert info["mode"] == "incremental", (step, info)
            assert info["recomputed"] < total, \
                "incremental apply recomputed the whole pool"
            saw_incremental = True
            ref = OSDMapMapping()
            ref.update(m, batched=False)
            assert mapping.by_pg == ref.by_pg, "step %d" % step
            assert {o: sorted(pgs, key=str)
                    for o, pgs in mapping.by_osd.items() if pgs} == \
                   {o: sorted(pgs, key=str)
                    for o, pgs in ref.by_osd.items() if pgs}, \
                "by_osd divergence at step %d" % step
        assert saw_incremental

    def test_mapping_falls_back_on_weight_change(self):
        """A reweight moves raw placements: the mapping must take the
        full-rebuild path, not pretend the overlay math covers it."""
        m = osdmaptool.create_simple(8, pg_num=32, hosts=4)
        mapping = OSDMapMapping()
        mapping.update(m, batched=False)
        inc = Incremental(m.epoch + 1)
        inc.new_weight[0] = 0x8000
        m.apply_incremental(inc)
        info = mapping.apply_incremental(m, inc, batched=False)
        assert info["mode"] == "full"
        ref = OSDMapMapping()
        ref.update(m, batched=False)
        assert mapping.by_pg == ref.by_pg


# ---------------------------------------------------------------------------
# mon-side: inc ring, batching, trim-floor fallback, re-push, status


class TestMonMapPipeline:
    def test_batched_catchup_and_wire_accounting(self):
        """A subscriber N epochs behind catches up through frames of
        at most osd_map_message_max incrementals each, and the inc
        path ships far fewer bytes than re-sending full maps."""
        conf = dict(FAST)
        conf["osd_map_message_max"] = 4
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            mon = cluster.leader()
            # a stale follower snapshotted before the churn
            stale = encoding.decode_any(
                encoding.encode_any(mon.osdmon.osdmap))
            _churn_epochs(client, cluster, 10)
            full_size = len(encoding.encode_any(mon.osdmon.osdmap))
            frames = 0
            inc_bytes = 0
            while True:
                m = mon.osdmon.build_map_message(stale.epoch)
                if m is None:
                    break
                frames += 1
                assert m.full_map is None, \
                    "above the trim floor yet got a full map"
                assert 1 <= len(m.incrementals) <= 4
                for inc in m.incrementals:
                    inc_bytes += len(encoding.encode_any(inc))
                    stale.apply_incremental(inc)
                assert frames < 50
            lag = mon.osdmon.osdmap.epoch - stale.epoch
            assert lag == 0
            assert encoding.encode_any(stale) == \
                encoding.encode_any(mon.osdmon.osdmap)
            assert frames >= 3, "10+ epochs should need >=3 frames of 4"
            # sub-linear wire claim at test scale: shipping the incs
            # must beat shipping one full map per frame
            assert inc_bytes < frames * full_size, \
                "incs (%d B over %d frames) not cheaper than full " \
                "maps (%d B each)" % (inc_bytes, frames, full_size)
        finally:
            cluster.stop()

    def test_trim_floor_fallback_ships_one_full_map(self):
        """A subscriber below mon_min_osdmap_epochs' trim floor gets
        EXACTLY one full map, never an unbounded inc chain."""
        conf = dict(FAST)
        conf["mon_min_osdmap_epochs"] = 4
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            mon = cluster.leader()
            behind_epoch = cluster.osdmap_epoch()
            _churn_epochs(client, cluster, 12)
            assert mon.osdmon.first_committed() > behind_epoch + 1, \
                "ring never trimmed past the stale epoch"
            m = mon.osdmon.build_map_message(behind_epoch)
            assert m is not None and m.full_map is not None
            assert not m.incrementals
            caught = encoding.decode_any(m.full_map)
            assert caught.epoch == m.epoch
            # exactly one frame: at the shipped epoch there is nothing
            # further to send
            assert mon.osdmon.build_map_message(caught.epoch) is None \
                or caught.epoch < mon.osdmon.osdmap.epoch
        finally:
            cluster.stop()

    def test_repush_is_bounded_per_subscriber(self):
        """The mon tick re-pushes catch-up frames to a lagging
        subscriber, but a subscriber that never renews (dead client)
        stops getting frames after 8 strikes."""
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            mon = cluster.leader()
            _churn_epochs(client, cluster, 4)
            fake = ("127.0.0.1", 65001)
            sent = []
            orig = mon.msgr.send_message

            def spy(msg, addr):
                if tuple(addr) == fake:
                    sent.append(msg)
                    return
                return orig(msg, addr)

            mon.msgr.send_message = spy
            try:
                with mon._lock:
                    mon._subscribers[fake] = 1
                for _ in range(12):
                    mon._repush_lagging_subs()
                    state = mon._sub_repush.get(fake)
                    if state is not None:
                        state[0] = 0.0     # defeat the 1/s rate limit
                assert len(sent) == 8, \
                    "re-push not strike-bounded: %d frames" % len(sent)
                for m in sent:
                    assert m.get_type() == "MOSDMap"
                # progress rearms the strikes: the subscriber reports
                # a newer (still lagging) epoch and gets frames again
                with mon._lock:
                    mon._subscribers[fake] = 2
                mon._repush_lagging_subs()
                assert len(sent) == 9
            finally:
                mon.msgr.send_message = orig
        finally:
            cluster.stop()

    def test_osdmap_status_surfaces(self):
        """'osdmap status' (asok) and 'osd map status' (mon command)
        dump ring span, trim floor and the laggiest subscriber."""
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            mon = cluster.leader()
            _churn_epochs(client, cluster, 5)
            res, outs, doc = client.mon_command(
                {"prefix": "osd map status"})
            assert res == 0, outs
            assert doc["epoch"] == mon.osdmon.osdmap.epoch
            assert doc["ring_epochs"] >= 5
            assert doc["ring_span"][0] == doc["trim_floor"]
            assert doc["ring_span"][1] == doc["epoch"]
            assert doc["ring_bytes"] > 0
            assert doc["subscribers"] >= 1
            lag = doc["laggiest_subscriber"]
            assert lag is None or lag["lag_epochs"] >= 0
            # asok lane: register against a real admin socket
            import os
            import tempfile
            if mon.ctx.admin_socket is None:
                path = os.path.join(tempfile.mkdtemp(), "mon.asok")
                mon.ctx.init_admin_socket(path)
            mon.register_admin_commands()
            mon.register_admin_commands()   # idempotent
            out = mon.ctx.admin_socket.execute("osdmap status", {})
            assert out["trim_floor"] == doc["trim_floor"]
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# client-side: map-advance throttle


class _FakeMsgr:
    def __init__(self):
        self.sent = []
        self.my_addr = ("127.0.0.1", 59999)

    def add_dispatcher_tail(self, d):
        pass

    def send_message(self, msg, addr):
        self.sent.append((msg, tuple(addr)))


class TestMapAdvanceThrottle:
    def _mk(self, max_advance: int):
        from ceph_tpu.mon.mon_client import MonClient
        mc = MonClient({0: ("127.0.0.1", 1)}, _FakeMsgr(), "osd.0")
        mc.map_max_advance = max_advance
        return mc

    def test_advance_slices_respect_budget(self):
        from ceph_tpu.msg.message import MOSDMap
        mc = self._mk(3)
        advances = []
        mc.map_callbacks.append(lambda m: advances.append(m.epoch))
        base = osdmaptool.create_simple(4, pg_num=8)
        mon = base.clone()
        incs = []
        for _ in range(11):
            inc = Incremental(mon.epoch + 1)
            inc.new_weight[0] = 0x10000
            mon.apply_incremental(inc)
            incs.append(encoding.decode_any(encoding.encode_any(inc)))
        mc._handle_osdmap(MOSDMap(
            full_map=encoding.encode_any(base),
            incrementals=incs, epoch=mon.epoch))
        # first drain: full map + 3 incs
        assert mc.osdmap.epoch == base.epoch + 3
        assert mc.map_lag_epochs() == mon.epoch - mc.osdmap.epoch
        epochs = [mc.osdmap.epoch]
        for _ in range(4):
            mc.renew_subs(min_interval=0.0)
            epochs.append(mc.osdmap.epoch)
        assert epochs == [base.epoch + 3, base.epoch + 6,
                          base.epoch + 9, mon.epoch, mon.epoch]
        assert mc.map_lag_epochs() == 0
        assert not mc._inc_backlog
        assert advances, "map callbacks never fired"
        assert encoding.encode_any(mc.osdmap) == \
            encoding.encode_any(mon)

    def test_gap_triggers_resubscribe(self):
        """A dropped frame leaves a hole: the client must re-subscribe
        at its current epoch instead of wedging on the backlog."""
        from ceph_tpu.msg.message import MOSDMap
        mc = self._mk(150)
        base = osdmaptool.create_simple(4, pg_num=8)
        mon = base.clone()
        incs = []
        for _ in range(4):
            inc = Incremental(mon.epoch + 1)
            inc.new_weight[1] = 0x10000
            mon.apply_incremental(inc)
            incs.append(inc)
        # deliver the full map, then ONLY the last two incs (the first
        # two frames were "dropped")
        mc._handle_osdmap(MOSDMap(full_map=encoding.encode_any(base),
                                  incrementals=[], epoch=base.epoch))
        mc.msgr.sent.clear()
        mc._handle_osdmap(MOSDMap(incrementals=incs[2:],
                                  epoch=mon.epoch))
        assert mc.osdmap.epoch == base.epoch   # cannot apply past gap
        assert mc.map_lag_epochs() == 4
        subs = [m for m, _ in mc.msgr.sent
                if m.get_type() == "MMonSubscribe"]
        assert subs and subs[-1].start_epoch == base.epoch
        # the mon answers with the missing span: now it all applies
        mc._handle_osdmap(MOSDMap(incrementals=incs[:2],
                                  epoch=mon.epoch))
        assert mc.osdmap.epoch == mon.epoch
        assert mc.map_lag_epochs() == 0


# ---------------------------------------------------------------------------
# long-offline OSD: rejoin through the trim-floor full-map path


class TestTrimFloorRejoin:
    def test_long_offline_osd_rejoins_past_trim_floor(self):
        """An OSD that slept through more epochs than the mon retains
        incrementals for must rejoin via the one-full-map fallback and
        serve data again."""
        conf = dict(FAST)
        conf["mon_min_osdmap_epochs"] = 4
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "sleepy", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("sleepy")
            for i in range(6):
                ioctx.write_full("s%d" % i, b"payload-%d" % i * 64)
            victim = 2
            sleep_epoch = cluster.osdmap_epoch()
            store = cluster.stop_osd(victim)
            assert wait_until(
                lambda: cluster.leader().osdmon.osdmap.is_down(victim),
                15)
            _churn_epochs(client, cluster, 10, seed=3)
            mon = cluster.leader()
            assert mon.osdmon.first_committed() > sleep_epoch + 1, \
                "churn never pushed the trim floor past the sleeper"
            cluster.revive_osd(victim, store=store)
            client.mon_command({"prefix": "osd in", "id": victim})
            assert wait_until(cluster.all_osds_up, timeout=30)
            osd = cluster.osds[victim]
            assert wait_until(
                lambda: osd.osdmap.epoch >= mon.osdmon.osdmap.epoch
                - 1, timeout=30), \
                "revived osd stuck at epoch %d (mon at %d)" \
                % (osd.osdmap.epoch, mon.osdmon.osdmap.epoch)
            for i in range(6):
                assert ioctx.read("s%d" % i) == b"payload-%d" % i * 64
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# map-churn thrash riders under live traffic


class TestMapChurnRiders:
    def test_riders_drive_epochs_and_heal(self):
        """Deterministic rider pass: out/in storm, reweight sweep and
        a churn-pool resize under a live writer — epochs advance, the
        resize instantiates new PGs, and the cluster heals to every
        acked object intact."""
        cluster = MiniCluster(num_mons=1, num_osds=4,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "riderdata",
                                           size=2, pg_num=8)
            cluster.create_replicated_pool(client, "riderchurn",
                                           size=2, pg_num=4)
            ioctx = client.open_ioctx("riderdata")
            stop_evt = threading.Event()
            acked = []

            def writer():
                i = 0
                while not stop_evt.is_set():
                    try:
                        ioctx.write_full("r%d" % i, b"x%d" % i * 128)
                        acked.append(i)
                    except Exception:
                        pass
                    i += 1
                    time.sleep(0.02)

            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            th = Thrasher(cluster, seed=9, min_in=2, interval=0.2,
                          churn_pool="riderchurn")
            # riders pend into paxos proposes and coalesce freely
            # under load — on a starved box ALL of them can merge
            # into one commit, so wait for a commit between riders
            # instead of demanding a fixed total afterwards
            e0 = cluster.osdmap_epoch()
            assert th.out_in_storm(count=2)
            assert wait_until(
                lambda: cluster.osdmap_epoch() >= e0 + 1, timeout=30)
            e1 = cluster.osdmap_epoch()
            assert th.reweight_sweep(count=2)
            assert wait_until(
                lambda: cluster.osdmap_epoch() >= e1 + 1, timeout=30)
            e2 = cluster.osdmap_epoch()
            assert th.pool_resize(grow_by=4) == 8
            assert wait_until(
                lambda: cluster.osdmap_epoch() >= e2 + 1, timeout=30)
            assert cluster.osdmap_epoch() >= e0 + 3
            # the split instantiated PGs: some OSD holds a riderchurn
            # PG with ps >= 4
            pool_id = client.pool_id("riderchurn")

            def split_pgs_exist():
                return any(k.pool == pool_id and k.ps >= 4
                           for osd in cluster.osds.values()
                           for k in list(osd.pgs))
            assert wait_until(split_pgs_exist, timeout=30), \
                "pool resize never instantiated the new PGs"
            th.stop_and_heal(timeout=60)

            # weights restored: no lingering override (the restore
            # pends until the next paxos propose)
            def weights_restored():
                m = cluster.leader().osdmon.osdmap
                return all(m.osd_weight[o] == 0x10000
                           for o in cluster.osds)
            assert wait_until(weights_restored, timeout=30)

            def healthy():
                _, _, data = client.mon_command({"prefix": "health"})
                return bool(data) and \
                    data.get("status") == "HEALTH_OK"
            assert wait_until(healthy, timeout=60)
            # churn may block (not fail) in-flight writes; once healed
            # the writer must make progress again
            n_heal = len(acked)
            assert wait_until(lambda: len(acked) > n_heal + 5,
                              timeout=30), \
                "IO never resumed after heal (%d acked)" % len(acked)
            stop_evt.set()
            wt.join(timeout=10)
            for i in list(acked):
                assert ioctx.read("r%d" % i) == b"x%d" % i * 128, i
        finally:
            cluster.stop()

    def test_peering_gate_dump_reaches_asok(self):
        """The peering reserver rides dump_reservations and 'osdmap
        status' on the OSD asok."""
        import os
        import tempfile
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "gated", size=2,
                                           pg_num=8)
            osd = cluster.osds[0]
            assert "peering" in osd.reservations
            assert osd.peering_gate
            doc = osd._osdmap_status()
            assert doc["epoch"] == osd.osdmap.epoch
            assert doc["map_max_advance"] == 150
            assert doc["peering_gate"] is True
            assert doc["lag_epochs"] >= 0
            # all slots drain back once the fresh pool finishes peering
            assert wait_until(
                lambda: osd._osdmap_status()["peering_active"] == 0,
                timeout=30), osd._osdmap_status()
            # p99 lane has samples once any PG peered
            assert wait_until(
                lambda: any(o.peering_p99() >= 0.0
                            and len(o._peering_durations) > 0
                            for o in cluster.osds.values()),
                timeout=20), "no peering durations recorded"
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# huge-map balancer convergence (tier-1 64-OSD variant; 1000-OSD slow)


def _converge(m: OSDMap, changes_per_sweep: int, max_changes: int,
              rounds: int):
    from ceph_tpu.osd.balancer import calc_pg_upmaps, eval_distribution
    before = eval_distribution(m, use_device=True)
    res = calc_pg_upmaps(m, max_deviation_ratio=0.05,
                         max_changes=max_changes, use_device=True,
                         changes_per_sweep=changes_per_sweep)
    assert res.sweeps <= rounds, \
        "needed %d sweeps (cap %d)" % (res.sweeps, rounds)
    inc = Incremental(m.epoch + 1)
    res.apply_to(inc)
    m.apply_incremental(inc)
    after = eval_distribution(m, use_device=True)
    return before, res, after


class TestHugeMapConvergence:
    def test_64osd_batched_sweep_converges(self):
        from .test_balancer import assert_failure_domains_intact
        m = osdmaptool.create_simple(64, pg_num=1024, pool_size=3,
                                     hosts=16)
        before, res, after = _converge(m, changes_per_sweep=16,
                                       max_changes=400, rounds=60)
        assert after.total_deviation <= before.total_deviation
        worst = max(abs(after.deviation(o)) / t
                    for o, t in after.targets.items() if t > 0)
        assert worst <= 0.15, (worst, res.num_changed)
        # the batch amortization actually batched: far fewer sweeps
        # than accepted changes
        if res.num_changed > 32:
            assert res.sweeps < res.num_changed
        assert_failure_domains_intact(m)

    @pytest.mark.slow
    def test_1000osd_map_converges_via_mesh_sweep(self):
        """Scale leg: a 1000-OSD map balances within a bounded round
        count, never violating the rule's failure-domain separation
        (sampled).  The bulk sweeps run the compiled host mapper (the
        honest comparator on a CPU-only host — cf. bench.py's CRUSH
        row); a sampled mesh_do_rule pass gates that the mesh-sharded
        device sweep is bit-identical on the SAME balanced map, so on
        real hardware the full-width sweep is interchangeable."""
        from ceph_tpu.crush.batched import mesh_do_rule
        from ceph_tpu.osd.balancer import (calc_pg_upmaps,
                                           eval_distribution,
                                           parent_index,
                                           parent_of_type,
                                           rule_failure_domain)
        m = osdmaptool.create_simple(1000, pg_num=32768, pool_size=3,
                                     hosts=250)
        before = eval_distribution(m, use_native=True)
        res = calc_pg_upmaps(m, max_deviation_ratio=0.1,
                             max_changes=3000, use_native=True,
                             changes_per_sweep=128)
        assert res.sweeps <= 40, res.sweeps
        inc = Incremental(m.epoch + 1)
        res.apply_to(inc)
        m.apply_incremental(inc)
        after = eval_distribution(m, use_native=True)
        assert after.total_deviation <= before.total_deviation
        worst = max(abs(after.deviation(o)) / t
                    for o, t in after.targets.items() if t > 0)
        assert worst <= 0.25, (worst, res.num_changed, res.sweeps)
        # sampled CRUSH-constraint validation over the remapped PGs
        fd = rule_failure_domain(m.crush, 0)
        pindex = parent_index(m.crush)
        rng = random.Random(5)
        check = rng.sample(sorted(m.pg_upmap_items, key=str),
                           min(200, len(m.pg_upmap_items)))
        for pgid in check:
            up, _, _, _ = m.pg_to_up_acting_osds(pgid)
            osds = [o for o in up if o != CRUSH_ITEM_NONE]
            assert len(set(osds)) == len(osds), (pgid, up)
            parents = [parent_of_type(m.crush, o, fd, pindex)
                       for o in osds]
            assert len(set(parents)) == len(parents), (pgid, up)
        # mesh-sweep parity on the balanced map: 256 sampled seeds
        # through the mesh-sharded device kernel vs the native rows
        from ceph_tpu.native import crush_do_rule_batch_native
        pool = m.pools[0]
        import numpy as np
        sample_ps = rng.sample(range(pool.pg_num), 256)
        seeds = np.array([pool.raw_pg_to_pps(PGID(0, ps))
                          for ps in sample_ps], dtype=np.int64)
        w = m._weight_vector()
        mesh_rows = mesh_do_rule(m.crush, pool.crush_rule, seeds,
                                 pool.size, w, choose_args=0)
        nat_rows = crush_do_rule_batch_native(
            m.crush, pool.crush_rule, seeds, pool.size, w,
            choose_args=0)
        for i in range(len(seeds)):
            dev = [int(v) for v in mesh_rows[i]
                   if int(v) != CRUSH_ITEM_NONE]
            assert dev == nat_rows[i], \
                "mesh/native divergence at seed %d" % seeds[i]
