"""End-to-end op tracing (ZTracer analog) + OSD_SLOW_OPS health.

Covers the observability spine: span parent/child integrity across a
live mini-cluster EC write (client -> primary -> per-shard sub-ops,
stitched by trace id through the message envelope), per-shard span
count == k+m, TPU device h2d/compute/d2h segments on a batched encode,
the zero-allocation disabled path, the admin-socket dump_tracing /
trace reset surface, the `trace tree` renderer, perf schema/reset, and
the slow-op -> OSD_SLOW_OPS health round trip.
"""

import time

import numpy as np
import pytest

from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.config import Config
from ceph_tpu.common.tracer import (NULL_SPAN, SpanCollector,
                                    device_segments, render_tree,
                                    trace_ctx)

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}


class TestSpanCollector:
    def test_disabled_allocates_no_spans(self):
        conf = Config({"osd_tracing": False})
        tracer = SpanCollector(conf=conf)
        span = tracer.start_trace("op")
        assert span is NULL_SPAN
        assert not span.valid()
        with span.child("sub") as sub:
            sub.keyval("k", 1)
            sub.event("e")
            sub.child_interval("i", 0.0, 1.0)
        assert tracer.continue_trace("x", 123, 45) is NULL_SPAN
        assert tracer.dump() == []
        assert trace_ctx(span) == (0, 0)

    def test_config_hot_toggle(self):
        conf = Config({"osd_tracing": False})
        tracer = SpanCollector(conf=conf)
        assert tracer.start_trace("x") is NULL_SPAN
        conf.set_val("osd_tracing", True)
        conf.apply_changes()
        assert tracer.enabled
        tracer.start_trace("y").finish()
        assert len(tracer.dump()) == 1

    def test_sampling_one_in_n(self):
        conf = Config({"osd_tracing": True, "osd_tracing_sample": 4})
        tracer = SpanCollector(conf=conf)
        real = sum(tracer.start_trace("s").valid() for _ in range(16))
        assert real == 4
        # sampled-out roots propagate nullness to the whole subtree
        assert tracer.continue_trace("c", 0, 0) is NULL_SPAN

    def test_parent_child_and_continue(self):
        tracer = SpanCollector()
        tracer.enabled = True
        root = tracer.start_trace("client_op", "client.0")
        child = root.child("messenger")
        # the envelope context stitches a second collector's spans
        remote = SpanCollector(endpoint="osd.1")
        remote.enabled = True
        t_id, p_id = trace_ctx(child)
        osd_span = remote.continue_trace("osd_op", t_id, p_id)
        assert osd_span.trace_id == root.trace_id
        assert osd_span.parent_id == child.span_id
        osd_span.finish()
        child.finish()
        root.finish()
        spans = tracer.dump() + remote.dump()
        by_name = {s["name"]: s for s in spans}
        assert by_name["messenger"]["parent_id"] == root.span_id
        assert len({s["trace_id"] for s in spans}) == 1

    def test_child_interval_backfill(self):
        tracer = SpanCollector()
        tracer.enabled = True
        root = tracer.start_trace("op")
        now = time.monotonic()
        iv = root.child_interval("queued", now - 0.5, now, batch=3)
        assert iv.valid()
        root.finish()
        doc = [s for s in tracer.dump() if s["name"] == "queued"][0]
        assert 0.45 < doc["duration"] < 0.55
        assert doc["keyvals"] == {"batch": 3}

    def test_ring_capacity(self):
        tracer = SpanCollector(capacity=3)
        tracer.enabled = True
        for i in range(6):
            tracer.start_trace("s%d" % i).finish()
        assert [s["name"] for s in tracer.dump()] == ["s3", "s4", "s5"]

    def test_admin_socket_surface(self, tmp_path):
        asok = AdminSocket(str(tmp_path / "t.asok"))
        tracer = SpanCollector()
        tracer.enabled = True
        tracer.register_admin_commands(asok)
        span = tracer.start_trace("op")
        span.finish()
        doc = asok.execute("dump_tracing")
        assert doc["num_spans"] == 1 and doc["enabled"]
        # filter by trace id (string form accepted, the CLI spelling)
        doc = asok.execute("dump_tracing",
                           {"trace_id": str(span.trace_id)})
        assert doc["num_spans"] == 1
        assert asok.execute("dump_tracing",
                            {"trace_id": span.trace_id + 1}
                            )["num_spans"] == 0
        assert asok.execute("trace reset") == {"reset": True}
        assert asok.execute("dump_tracing")["num_spans"] == 0

    def test_render_tree_self_times(self):
        tracer = SpanCollector()
        tracer.enabled = True
        root = tracer.start_trace("osd_op", "osd.0")
        time.sleep(0.01)
        with root.child("store_commit"):
            time.sleep(0.01)
        root.finish()
        out = render_tree(tracer.dump())
        assert "osd_op" in out and "store_commit" in out
        assert "self" in out
        # rendering a forest with a missing parent must not crash
        orphans = [{"trace_id": 1, "span_id": 2, "parent_id": 99,
                    "name": "x", "endpoint": "osd.1", "start": 0.0,
                    "start_wall": 0.0, "duration": 0.1, "keyvals": {},
                    "events": []}]
        assert "x" in render_tree(orphans)
        assert render_tree([]) == "(no spans)"


class TestDeviceSegments:
    def test_segments_sum_within_wall(self):
        batch = np.arange(64, dtype=np.uint8).reshape(1, 4, 16)
        t0 = time.perf_counter()
        out, seg = device_segments(
            lambda b: np.asarray(b, dtype=np.uint8) ^ 0xFF, batch)
        wall = time.perf_counter() - t0
        assert np.array_equal(out, batch ^ 0xFF)
        assert set(seg) == {"h2d", "compute", "d2h"}
        assert all(v >= 0 for v in seg.values())
        assert sum(seg.values()) <= wall * 1.05 + 1e-4


class _XorCodec:
    """Tiny stand-in codec: encode_batch works on host or device."""

    def encode_batch(self, batch):
        return batch ^ 0x5A


class TestDispatcherTracing:
    def test_device_segments_on_batched_encode(self):
        from ceph_tpu.osd.tpu_dispatch import TpuDispatcher
        tracer = SpanCollector()
        tracer.enabled = True
        disp = TpuDispatcher(max_batch=4, max_delay=0.001,
                             tracer=tracer)
        try:
            codec = _XorCodec()
            batch = np.arange(32, dtype=np.uint8).reshape(2, 4, 4)
            root = tracer.start_trace("op")
            out = disp.encode(codec, batch, trace=root)
            root.finish()
            assert np.array_equal(out, batch ^ 0x5A)
            names = {s["name"] for s in tracer.dump()}
            assert {"tpu_queue", "tpu_device",
                    "h2d", "compute", "d2h"} <= names
            # h2d/compute/d2h nest under the tpu_device span
            spans = tracer.dump()
            dev = [s for s in spans if s["name"] == "tpu_device"][0]
            for leg in ("h2d", "compute", "d2h"):
                leg_span = [s for s in spans if s["name"] == leg][0]
                assert leg_span["parent_id"] == dev["span_id"]
            assert disp.perf.get("l_tpu_dispatches") >= 1
            assert disp.perf.dump()["l_tpu_compute"]["avgcount"] >= 1
        finally:
            disp.shutdown()

    def test_disabled_tracer_no_spans_no_segments(self):
        """Disabled tracing mints ZERO spans on every path.  The
        depth-1 (legacy synchronous) path additionally measures no
        segments — its no-extra-device-syncs contract; the pipelined
        path gets stage intervals for free (the stages block per leg
        anyway), so its counters MAY advance, but spans still must
        not."""
        from ceph_tpu.osd.tpu_dispatch import TpuDispatcher
        tracer = SpanCollector()          # disabled
        disp = TpuDispatcher(tracer=tracer, pipeline_depth=1)
        try:
            out = disp.encode(_XorCodec(),
                              np.zeros((1, 2, 4), dtype=np.uint8))
            assert out.shape == (1, 2, 4)
            assert tracer.dump() == []
            assert disp.perf.dump()["l_tpu_compute"]["avgcount"] == 0
        finally:
            disp.shutdown()
        disp = TpuDispatcher(tracer=tracer)   # pipelined default
        try:
            out = disp.encode(_XorCodec(),
                              np.zeros((1, 2, 4), dtype=np.uint8))
            assert out.shape == (1, 2, 4)
            assert tracer.dump() == []        # still no span objects
        finally:
            disp.shutdown()


class TestPerfSchemaReset:
    def test_schema_and_reset_over_asok(self, tmp_path):
        from ceph_tpu.common.context import Context
        ctx = Context(name="t")
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        pc = (PerfCountersBuilder("osd")
              .add_u64_counter("op")
              .add_time_avg("op_latency")
              .add_histogram("l_osd_op_trace_us")
              .create_perf_counters())
        ctx.perf.add(pc)
        pc.inc("op", 3)
        pc.tinc("op_latency", 0.5)
        pc.hinc("l_osd_op_trace_us", 1000)
        asok = AdminSocket(str(tmp_path / "t.asok"))
        asok.register("perf schema",
                      lambda args: ctx.perf.perf_schema(), "")
        asok.register("perf reset",
                      lambda args: {"reset": ctx.perf.perf_reset(
                          args.get("key"))}, "")
        schema = asok.execute("perf schema")["osd"]
        assert schema["op"]["type"] == "u64_counter"
        assert schema["op_latency"]["type"] == "time_avg"
        assert schema["l_osd_op_trace_us"]["type"] == "histogram"
        assert schema["l_osd_op_trace_us"]["buckets"][0] == 2
        assert asok.execute("perf reset") == {"reset": ["osd"]}
        dumped = pc.dump()
        assert dumped["op"] == 0
        assert dumped["op_latency"]["avgcount"] == 0
        assert sum(dumped["l_osd_op_trace_us"]["buckets"]) == 0


class TestClusterTracing:
    def test_ec_write_stitches_cross_daemon_trace(self):
        """A single client write on a 3-OSD EC pool yields ONE stitched
        trace: client_op -> messenger -> osd_op -> {op_queue, pg_do_op,
        ec_encode (tpu_queue + tpu_device{h2d,compute,d2h}),
        sub_write(shard=i) x (k+m) -> ec_sub_write -> store span}."""
        from .cluster_util import MiniCluster, wait_until
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(
                client, "trace-ec",
                {"plugin": "jerasure", "technique": "reed_sol_van",
                 "k": "2", "m": "1", "w": "8"}, pg_num=1)
            assert cluster.wait_clean(client.pool_id("trace-ec"))
            ioctx = client.open_ioctx("trace-ec")
            payload = bytes(range(256)) * 16
            ioctx.write_full("tobj", payload)
            assert ioctx.read("tobj") == payload

            def all_spans():
                spans = list(client.tracer.dump())
                for osd in cluster.osds.values():
                    spans.extend(osd.tracer.dump())
                return spans

            def write_trace():
                spans = all_spans()
                roots = [s for s in spans if s["name"] == "client_op"
                         and "writefull" in str(s["keyvals"].get("op"))]
                if not roots:
                    return None
                tid = roots[0]["trace_id"]
                mine = [s for s in spans if s["trace_id"] == tid]
                names = [s["name"] for s in mine]
                subs = [n for n in names
                        if n.startswith("sub_write(shard=")]
                # the full tree lands asynchronously (replica commits)
                if len(subs) < 3 or "ec_sub_write" not in names:
                    return None
                return mine

            assert wait_until(lambda: write_trace() is not None)
            mine = write_trace()
            names = [s["name"] for s in mine]
            # messenger + queue + pg + per-shard + store + device legs
            for want in ("client_op", "messenger", "osd_op",
                         "op_queue", "pg_do_op", "ec_encode",
                         "ec_sub_write", "tpu_queue", "tpu_device",
                         "h2d", "compute", "d2h"):
                assert want in names, (want, sorted(set(names)))
            # per-shard sub-write span count equals k+m
            subs = [n for n in names if n.startswith("sub_write(shard=")]
            assert len(subs) == 3, subs
            # store-phase span present (MemStore: store_apply)
            assert "store_apply" in names
            # parent/child integrity: every non-root parent resolves
            # inside the stitched set
            ids = {s["span_id"] for s in mine}
            roots = [s for s in mine if not s["parent_id"]]
            assert len(roots) == 1 and roots[0]["name"] == "client_op"
            for s in mine:
                if s["parent_id"]:
                    assert s["parent_id"] in ids, s
            # one trace spans multiple daemons
            assert len({s["endpoint"] for s in mine}) >= 3
            # dump_tracing retrieval + the trace tree renderer
            tid = mine[0]["trace_id"]
            primary = next(
                osd for osd in cluster.osds.values()
                if any(s["name"] == "osd_op" for s in osd.tracer.dump()))
            import os
            asok = AdminSocket(os.path.join(
                "/tmp", "trace-test-%d.asok" % os.getpid()))
            primary.tracer.register_admin_commands(asok)
            doc = asok.execute("dump_tracing", {"trace_id": tid})
            assert doc["num_spans"] >= 1
            tree = render_tree(mine, trace_id=tid)
            assert "client_op" in tree and "sub_write" in tree
            assert "self" in tree
            # read path: per-shard sub_read spans + decode
            read_spans = [s for s in all_spans()
                          if s["name"].startswith("sub_read(shard=")]
            assert len(read_spans) >= 2          # k shards read
            assert any(s["name"] == "ec_decode" for s in all_spans())
        finally:
            cluster.stop()

    def test_disabled_tracing_cluster_records_nothing(self):
        from .cluster_util import MiniCluster
        conf = dict(FAST)
        conf["osd_tracing"] = False
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "quiet", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("quiet")
            ioctx.write_full("q", b"silent")
            assert ioctx.read("q") == b"silent"
            assert client.tracer.dump() == []
            for osd in cluster.osds.values():
                assert osd.tracer.dump() == []
        finally:
            cluster.stop()


class TestSlowOpsHealth:
    def test_slow_op_raises_and_clears_osd_slow_ops(self):
        """A wedged op raises OSD_SLOW_OPS in `ceph health` (via the
        MPGStats report into the HealthMonitor) and the check clears
        when the op drains."""
        from .cluster_util import MiniCluster, wait_until
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            osd = cluster.osds[0]
            osd.op_tracker.complaint_time = 0.05
            stuck = osd.op_tracker.create_request("wedged write")
            time.sleep(0.1)

            def health_checks():
                res, _, data = client.mon_command({"prefix": "health"})
                assert res == 0
                return data["checks"]

            assert wait_until(
                lambda: "OSD_SLOW_OPS" in health_checks())
            check = health_checks()["OSD_SLOW_OPS"]
            assert "slow" in check["summary"]
            assert any("osd.0" in d for d in check["detail"])
            stuck.mark_done()
            assert wait_until(
                lambda: "OSD_SLOW_OPS" not in health_checks())
        finally:
            cluster.stop()


@pytest.mark.slow
class TestSpanVolume:
    def test_span_volume_stress(self):
        """Span-volume stress: a deep, wide burst stays inside the
        bounded ring and dump/render remain responsive."""
        tracer = SpanCollector(capacity=4096)
        tracer.enabled = True
        for i in range(20000):
            root = tracer.start_trace("op%d" % (i % 7))
            for j in range(4):
                with root.child("leg%d" % j) as leg:
                    leg.keyval("i", i)
            root.finish()
        spans = tracer.dump()
        assert len(spans) == 4096
        out = render_tree(spans[-50:])
        assert out
