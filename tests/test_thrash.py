"""Chaos tests: workload under OSD churn and message-level faults.

Models teuthology's thrash suites
(qa/suites/rados/thrash-erasure-code/, qa/tasks/ceph_manager.py
Thrasher) and the msgr-failures fragments ('ms inject socket
failures') at in-process scale: a writer keeps writing checksummed
objects while the thrasher kills/revives OSDs; when the dust settles
every acknowledged object must read back bit-exact.
"""

import hashlib
import threading
import time

import pytest

from .cluster_util import MiniCluster, wait_until
from .thrasher import Thrasher

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


def payload_for(i: int) -> bytes:
    seed = ("obj-%d" % i).encode()
    return hashlib.sha256(seed).digest() * 200   # 6.4k, content-derived


class _Writer(threading.Thread):
    """Foreground workload: keep writing; remember what was ACKED."""

    def __init__(self, ioctx, stop_evt):
        super().__init__(name="thrash-writer", daemon=True)
        self.ioctx = ioctx
        self.stop_evt = stop_evt
        self.acked: list[int] = []
        self.write_errors = 0

    def run(self):
        i = 0
        while not self.stop_evt.is_set():
            try:
                self.ioctx.write_full("obj-%d" % i, payload_for(i))
                self.acked.append(i)
            except Exception:
                # a write may time out mid-failover; only ACKED writes
                # carry a durability promise
                self.write_errors += 1
            i += 1
            time.sleep(0.02)


class TestThrashReplicated:
    def test_workload_survives_osd_churn(self):
        cluster = MiniCluster(num_mons=1, num_osds=4,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "thrash", size=2,
                                           pg_num=8)
            ioctx = client.open_ioctx("thrash")
            stop_evt = threading.Event()
            writer = _Writer(ioctx, stop_evt)
            # min_in=3 of 4: at most one osd down at a time, so a
            # size-2 pool always keeps one replica serving (the
            # reference thrasher maintains the same invariant via
            # min_in/min_live)
            thrasher = Thrasher(cluster, seed=7, min_in=3,
                                interval=1.5, revive_delay=0.5)
            writer.start()
            thrasher.start()
            # adaptive window instead of a fixed sleep: run until the
            # workload has demonstrably made progress through several
            # kill cycles (a loaded box slows peering; a fixed window
            # then starves the writer and flakes the floor assertion)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                kills = [a for a in thrasher.log if a[0] == "kill"]
                if len(writer.acked) > 15 and len(kills) >= 2:
                    break
                time.sleep(0.5)
            thrasher.stop_and_heal(timeout=60)
            stop_evt.set()
            writer.join(timeout=10)
            kills = [a for a in thrasher.log if a[0] == "kill"]
            assert kills, "thrasher never killed anything"
            assert len(writer.acked) > 10, \
                "workload starved: %d acked in 60s" % len(writer.acked)
            # every acknowledged write must read back bit-exact
            deadline = time.monotonic() + 30
            missing = list(writer.acked)
            while missing and time.monotonic() < deadline:
                still = []
                for i in missing:
                    try:
                        if ioctx.read("obj-%d" % i) != payload_for(i):
                            still.append(i)
                    except Exception:
                        still.append(i)
                missing = still
                if missing:
                    time.sleep(0.5)
            assert not missing, \
                "%d acked objects lost after thrash (e.g. %s); log=%s" \
                % (len(missing), missing[:5], thrasher.log)
            # the cluster event journal interleaves what the thrasher
            # DID (kill/revive) with how the cluster REACTED (osdmap
            # down/out epochs, health transitions)
            def journaled():
                _, _, events = client.mon_command(
                    {"prefix": "events last", "num": 500})
                types = {e.get("type") for e in events or []}
                return "thrash" in types and "osdmap" in types
            assert wait_until(journaled, timeout=15), \
                "thrash/osdmap events never reached the journal"
            _, _, events = client.mon_command(
                {"prefix": "events last", "num": 500})
            thrash_seqs = [e["seq"] for e in events
                           if e.get("type") == "thrash"]
            assert thrash_seqs, "no thrash events journaled"
            # at least one cluster-reaction event committed AFTER the
            # first injected fault: the journal shows cause before
            # effect, in one ordered stream
            reaction = [e["seq"] for e in events
                        if e.get("type") in ("osdmap", "health")
                        and e["seq"] > thrash_seqs[0]]
            assert reaction, \
                "no cluster reaction interleaved after the first " \
                "fault: %s" % [(e.get("seq"), e.get("type"))
                               for e in events]
        finally:
            cluster.stop()


class TestDivergentDeletes:
    def test_delete_during_downtime_not_resurrected(self):
        """Object deleted while a replica was down: when the replica
        revives (with its stale copy) the delete must propagate to it,
        not the stale copy back into the cluster."""
        from ceph_tpu.client.rados import RadosError
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "deldiv", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("deldiv")
            ioctx.write_full("ghost", b"soon to be deleted" * 100)
            m = client.osdmap
            pool_id = client.pool_id("deldiv")
            pgid = m.pools[pool_id].raw_pg_to_pg(
                m.object_to_pg(pool_id, "ghost"))
            _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
            victim = [o for o in acting if o != primary][0]
            store = cluster.stop_osd(victim)
            assert wait_until(
                lambda: cluster.leader().osdmon.osdmap.is_down(victim),
                15)
            ioctx.remove("ghost")
            with pytest.raises(RadosError):
                ioctx.stat("ghost")
            # revive with the stale store still holding the object
            cluster.revive_osd(victim, store=store)
            client.mon_command({"prefix": "osd in", "id": victim})
            assert wait_until(cluster.all_osds_up, timeout=20)
            # recovery must propagate the delete to the revived osd...
            cid = ("pg", str(pgid), -1)

            def ghost_gone():
                osd = cluster.osds.get(victim)
                return osd is not None and \
                    not osd.store.exists(cid, "ghost")
            assert wait_until(ghost_gone, 20), \
                "stale copy survived on the revived osd"
            # ...and the object must stay deleted cluster-wide
            with pytest.raises(RadosError):
                ioctx.stat("ghost")
        finally:
            cluster.stop()

    def test_recreated_object_not_deleted_by_stale_log(self):
        """Delete then RE-CREATE at a higher version: the recreation
        must survive recovery (the delete record is superseded)."""
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "recre", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("recre")
            ioctx.write_full("phoenix", b"first life")
            m = client.osdmap
            pool_id = client.pool_id("recre")
            pgid = m.pools[pool_id].raw_pg_to_pg(
                m.object_to_pg(pool_id, "phoenix"))
            _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
            victim = [o for o in acting if o != primary][0]
            store = cluster.stop_osd(victim)
            assert wait_until(
                lambda: cluster.leader().osdmon.osdmap.is_down(victim),
                15)
            ioctx.remove("phoenix")
            ioctx.write_full("phoenix", b"second life")
            cluster.revive_osd(victim, store=store)
            client.mon_command({"prefix": "osd in", "id": victim})
            assert wait_until(cluster.all_osds_up, timeout=20)
            deadline = time.time() + 20
            while time.time() < deadline:
                if ioctx.read("phoenix") == b"second life":
                    break
                time.sleep(0.3)
            assert ioctx.read("phoenix") == b"second life"
        finally:
            cluster.stop()


class TestMessageFaults:
    def test_io_completes_under_socket_failures(self):
        """'ms inject socket failures' analog: lossless retransmit must
        mask injected drops and delays."""
        conf = dict(FAST)
        conf["ms_inject_socket_failures"] = 30   # drop 1 in 30
        conf["ms_inject_delay_max"] = 0.01
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "lossy", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("lossy")
            for i in range(25):
                try:
                    ioctx.write_full("m%d" % i, payload_for(i),
                                     timeout=10.0)
                except Exception:
                    # one retry after a map nudge: under triple fault
                    # injection a rare op can ride out its window; the
                    # retransmit machinery must mask it on the retry
                    client.mon_client.sub_want()
                    ioctx.write_full("m%d" % i, payload_for(i))
            for i in range(25):
                assert ioctx.read("m%d" % i) == payload_for(i)
        finally:
            cluster.stop()


class TestEIOInjection:
    def test_ec_read_reconstructs_around_injected_eio(self):
        """qa/standalone/erasure-code/test-erasure-eio.sh analog: a
        shard that returns EIO must not fail the client read — the
        backend reconstructs from the other shards."""
        cluster = MiniCluster(num_mons=1, num_osds=4,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "eiopool",
                                   {"plugin": "jerasure",
                                    "technique": "reed_sol_van",
                                    "k": "2", "m": "1"}, pg_num=4)
            ioctx = client.open_ioctx("eiopool")
            payload = payload_for(99)
            ioctx.write_full("eobj", payload)
            assert ioctx.read("eobj") == payload
            # find one shard's holder and poison exactly that object
            poisoned = 0
            for osd in cluster.osds.values():
                for cid in osd.store.list_collections():
                    if "eobj" in osd.store.list_objects(cid):
                        osd.store.inject_read_error(cid, "eobj")
                        poisoned += 1
                        break
                if poisoned:
                    break
            assert poisoned == 1
            deadline = time.monotonic() + 15
            data = None
            while time.monotonic() < deadline:
                try:
                    data = ioctx.read("eobj")
                    if data == payload:
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            assert data == payload, "EIO was not reconstructed around"
        finally:
            cluster.stop()


class TestSnapThrash:
    def test_snaps_and_rollbacks_survive_osd_churn(self):
        """The EC-thrash-with-snaps workload shape
        (qa/erasure-code/ec-rados-plugin=jerasure*.yaml runs snap_create/
        snap_remove/rollback under churn): concurrent snaps, writes and
        rollbacks with OSDs dying must preserve every acked state."""
        from .cluster_util import MiniCluster, wait_until
        from .thrasher import Thrasher
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02}
        cluster = MiniCluster(num_mons=1, num_osds=5,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "snapthrash",
                                           size=3, pg_num=4)
            ioctx = client.open_ioctx("snapthrash")
            thrasher = Thrasher(cluster, seed=11, min_in=3,
                                interval=0.4)
            thrasher.start()
            import random
            rng = random.Random(3)
            snaps: dict[str, dict[str, bytes]] = {}   # snap -> oid-> data
            state: dict[str, bytes] = {}
            try:
                for step in range(30):
                    action = rng.random()
                    oid = "sobj-%d" % rng.randrange(4)
                    if action < 0.5 or not snaps:
                        data = bytes(rng.randbytes(256)) * 4
                        ioctx.write_full(oid, data, timeout=60)
                        state[oid] = data
                    elif action < 0.7 and len(snaps) < 4:
                        name = "ts-%d" % step
                        ioctx.create_snap(name)
                        snaps[name] = dict(state)
                    else:
                        name = rng.choice(sorted(snaps))
                        frozen = snaps[name]
                        if oid in frozen:
                            ioctx.rollback(oid, name)
                            state[oid] = frozen[oid]
            finally:
                thrasher.stop_and_heal(timeout=60)
            # every acked head state is intact
            for oid, want in state.items():
                assert ioctx.read(oid) == want, oid
            # and every snapshot still reads frozen-in-time data
            for name, frozen in snaps.items():
                sid = ioctx.lookup_snap(name)
                ioctx.snap_set_read(sid)
                try:
                    for oid, want in frozen.items():
                        assert ioctx.read(oid) == want, (name, oid)
                finally:
                    ioctx.snap_set_read(0)
        finally:
            cluster.stop()


class TestOpDedup:
    def test_duplicate_append_applies_once(self):
        """A retransmitted MOSDOp (same client tid — slow reply, lossy
        link) must not double-apply a non-idempotent op (Objecter
        reqid dedup semantics)."""
        from ceph_tpu.msg.message import MOSDOp
        from .cluster_util import MiniCluster
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "dup", size=3,
                                           pg_num=1)
            ioctx = client.open_ioctx("dup")
            ioctx.write_full("log", b"base|")
            # deliver the SAME append message twice straight into the
            # primary's dispatcher (a perfect retransmit)
            pgid, primary = client._target_for(ioctx.pool_id, "log")
            osd = cluster.osds[primary]
            msg = MOSDOp(client_id=77, tid=12345, pgid=pgid, oid="log",
                         ops=[("append", b"entry|")],
                         map_epoch=client.osdmap.epoch)
            msg.from_addr = client.msgr.my_addr
            dup = MOSDOp(client_id=77, tid=12345, pgid=pgid, oid="log",
                         ops=[("append", b"entry|")],
                         map_epoch=client.osdmap.epoch)
            dup.from_addr = client.msgr.my_addr
            osd._enqueue_client_op(msg)
            osd._enqueue_client_op(dup)
            import time
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ioctx.read("log") == b"base|entry|":
                    break
                time.sleep(0.05)
            # a third delivery AFTER completion replays the cached
            # reply without re-executing either
            dup2 = MOSDOp(client_id=77, tid=12345, pgid=pgid,
                          oid="log", ops=[("append", b"entry|")],
                          map_epoch=client.osdmap.epoch)
            dup2.from_addr = client.msgr.my_addr
            osd._enqueue_client_op(dup2)
            time.sleep(0.5)
            assert ioctx.read("log") == b"base|entry|"
        finally:
            cluster.stop()

    def test_appends_exact_under_lossy_links(self):
        """End to end: appends through a message-dropping transport
        land exactly once each."""
        from .cluster_util import MiniCluster
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02,
                "ms_inject_socket_failures": 40}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "lossy-app",
                                           size=3, pg_num=2)
            ioctx = client.open_ioctx("lossy-app")
            ioctx.write_full("journal", b"")
            want = b""
            for i in range(12):
                piece = ("rec%02d;" % i).encode()
                ioctx.append("journal", piece)
                want += piece
            assert ioctx.read("journal") == want
        finally:
            cluster.stop()

    def test_retransmit_after_primary_failover_not_reapplied(self):
        """The reqid rides the REPLICATED log, so a retransmit hitting
        the NEW primary after the old one died (having committed but
        never replied) replays the outcome instead of appending twice."""
        from ceph_tpu.msg.message import MOSDOp
        from .cluster_util import MiniCluster, wait_until
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "fo", size=3,
                                           pg_num=1)
            ioctx = client.open_ioctx("fo")
            ioctx.write_full("log", b"base|")
            pgid, primary = client._target_for(ioctx.pool_id, "log")

            msg = MOSDOp(client_id=5, tid=777, pgid=pgid, oid="log",
                         ops=[("append", b"once|")],
                         map_epoch=client.osdmap.epoch,
                         session="failover-session")
            msg.from_addr = client.msgr.my_addr
            cluster.osds[primary]._enqueue_client_op(msg)
            import time
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if ioctx.read("log") == b"base|once|":
                    break
                time.sleep(0.05)
            assert ioctx.read("log") == b"base|once|"

            # the primary dies having committed but (pretend) never
            # replied; the client retransmits to the new primary
            cluster.stop_osd(primary)
            assert wait_until(
                lambda: not cluster.leader().osdmon.osdmap.is_up(
                    primary), timeout=10)

            def new_primary_ready():
                _, p2 = client._target_for(ioctx.pool_id, "log")
                return p2 != primary and p2 != -1 and p2 in cluster.osds
            assert wait_until(new_primary_ready, timeout=15)
            _, p2 = client._target_for(ioctx.pool_id, "log")
            # wait for the new primary's PG to activate (merged log)
            def active():
                for k, pg in cluster.osds[p2].pgs.items():
                    if str(k) == str(pgid):
                        return pg.peer_state == "active"
                return False
            assert wait_until(active, timeout=15)

            dup = MOSDOp(client_id=5, tid=777, pgid=pgid, oid="log",
                         ops=[("append", b"once|")],
                         map_epoch=client.osdmap.epoch,
                         session="failover-session")
            dup.from_addr = client.msgr.my_addr
            cluster.osds[p2]._enqueue_client_op(dup)
            time.sleep(1.0)
            assert ioctx.read("log") == b"base|once|"
        finally:
            cluster.stop()


class TestPartitionChaos:
    def test_partition_marks_down_then_heals_to_health_ok(self):
        """Tentpole chaos gate: blackhole osd.a <-> osd.b while both
        stay mon-reachable -> heartbeat failure reports mark at least
        one of them down; heal the partition -> the cluster converges
        back to all-up and HEALTH_OK with every acked object intact."""
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "part", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("part")
            for i in range(6):
                ioctx.write_full("p%d" % i, payload_for(i))
            thrasher = Thrasher(cluster, seed=3)
            thrasher.partition(0, 1)
            assert ("partition", 0, 1) in thrasher.log

            def someone_down():
                m = cluster.leader().osdmon.osdmap
                return m.is_down(0) or m.is_down(1)
            assert wait_until(someone_down, timeout=30), \
                "partitioned peers never reported each other down"
            thrasher.heal()
            assert not thrasher.partitions
            assert wait_until(cluster.all_osds_up, timeout=30), \
                "cluster never re-converged after heal"

            def healthy():
                _, _, data = client.mon_command({"prefix": "health"})
                return bool(data) and data.get("status") == "HEALTH_OK"
            assert wait_until(healthy, timeout=40), \
                "no HEALTH_OK after heal: %s" % (
                    client.mon_command({"prefix": "health"})[1],)
            # durability across the partition: every acked object
            # reads back bit-exact
            for i in range(6):
                assert ioctx.read("p%d" % i) == payload_for(i), i
            assert not thrasher.errors, thrasher.errors
        finally:
            cluster.stop()


class TestMonThrash:
    def test_leader_bounce_mid_churn_converges(self):
        """Kill the paxos leader and boot a state-empty replacement
        while client IO runs: survivors re-elect, the rejoining mon
        full-syncs, and the quorum keeps taking writes."""
        cluster = MiniCluster(num_mons=3, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "monthrash",
                                           size=2, pg_num=4)
            ioctx = client.open_ioctx("monthrash")
            for i in range(4):
                ioctx.write_full("m%d" % i, payload_for(i))
            thrasher = Thrasher(cluster, seed=5)
            bounced = thrasher.thrash_mon()
            assert bounced is not None
            # quorum still takes maps/commands (client hunts past any
            # electing mon)
            assert wait_until(
                lambda: any(m.is_leader() for m in cluster.mons),
                timeout=30)
            for i in range(4, 8):
                ioctx.write_full("m%d" % i, payload_for(i),
                                 timeout=30.0)
            # the bounced rank catches up via the paxos full-state
            # sync: it must reach leader-or-peon with the pool present
            replacement = next(m for m in cluster.mons
                               if m.rank == bounced)

            def caught_up():
                if replacement.state not in ("leader", "peon"):
                    return False
                return any(p.name == "monthrash" for p in
                           replacement.osdmon.osdmap.pools.values())
            assert wait_until(caught_up, timeout=40), \
                "bounced mon.%d never rejoined: state=%s" \
                % (bounced, replacement.state)
            for i in range(8):
                assert ioctx.read("m%d" % i) == payload_for(i), i
            assert not thrasher.errors, thrasher.errors
        finally:
            cluster.stop()


class TestFullOsdProtection:
    def test_full_osd_rejects_writes_serves_reads_admits_deletes(self):
        """Full-ratio ladder end to end: shrink every store's nominal
        capacity so used_ratio crosses mon_osd_full_ratio -> client
        writes bounce with ENOSPC at admission, reads keep flowing,
        the mon raises OSD_FULL — then deletes (always admitted) free
        space and writes start succeeding again."""
        from ceph_tpu.client.rados import RadosError
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "fullpool", size=3,
                                           pg_num=4)
            ioctx = client.open_ioctx("fullpool")
            for i in range(8):
                ioctx.write_full("f%d" % i, payload_for(i))
            # shrink nominal capacity under the live usage: used_ratio
            # = used / max(capacity, used) -> 1.0 > full_ratio
            for osd in cluster.osds.values():
                osd.store.capacity_bytes = 1
            assert wait_until(
                lambda: all(o.is_full() for o in cluster.osds.values()),
                timeout=10)
            with pytest.raises(RadosError) as ei:
                ioctx.write_full("overflow", b"x" * 1024, timeout=15.0)
            assert ei.value.errno == 28, ei.value   # ENOSPC
            # reads are still served off the full osds
            assert ioctx.read("f0") == payload_for(0)
            # the mon derives OSD_FULL from the used_ratio riding
            # MPGStats
            def full_check_raised():
                _, _, data = client.mon_command(
                    {"prefix": "health detail"})
                return bool(data) and "OSD_FULL" in data.get(
                    "checks", {})
            assert wait_until(full_check_raised, timeout=30), \
                "OSD_FULL never raised"
            # deletes stay admitted (space-freeing): dig the cluster
            # out, then writes succeed again
            for i in range(8):
                ioctx.remove("f%d" % i)
            for osd in cluster.osds.values():
                osd.store.capacity_bytes = 4 << 30
            assert wait_until(
                lambda: not any(o.is_full()
                                for o in cluster.osds.values()),
                timeout=10)
            ioctx.write_full("after", b"room again")
            assert ioctx.read("after") == b"room again"

            def full_check_cleared():
                _, _, data = client.mon_command(
                    {"prefix": "health detail"})
                return bool(data) and "OSD_FULL" not in data.get(
                    "checks", {})
            assert wait_until(full_check_cleared, timeout=30), \
                "OSD_FULL never cleared"
        finally:
            cluster.stop()

    def test_backfillfull_osd_refuses_backfill_reservation(self):
        """A backfillfull osd answers MBackfillReserve requests with
        reject reason=toofull and the requesting PG parks in
        backfill_toofull instead of pushing into it."""
        # unit-level: exercise reserve_refusal directly on a daemon
        cluster = MiniCluster(num_mons=1, num_osds=2,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "bf", size=2,
                                           pg_num=2)
            ioctx = client.open_ioctx("bf")
            for i in range(4):
                ioctx.write_full("b%d" % i, payload_for(i))
            osd = cluster.osds[0]
            assert osd.reserve_refusal("backfill") is None
            assert osd.reserve_refusal("recovery") is None
            # used > 0 on every osd (size=2 of 2), so capacity=1 byte
            # drives used / max(capacity, used) to 1.0
            osd.store.capacity_bytes = 1
            osd._used_stat_cache = (0.0, -1e9)   # drop the 0.5s cache
            assert osd.is_backfillfull()
            assert osd.reserve_refusal("backfill") == "toofull"
            # recovery is only refused at FULL, which 1.0 also crosses
            assert osd.is_full()
            assert osd.reserve_refusal("recovery") == "toofull"
        finally:
            cluster.stop()


class TestAdmissionControl:
    def test_client_message_cap_blocks_reader_not_queue(self):
        """osd_client_message_cap regression: with the dispatch
        throttle armed, over-budget CLIENT messages park the reader
        (TCP backpressure) instead of growing an unbounded dispatch
        queue; releasing the budget admits the next message; non-client
        peers bypass the throttle entirely."""
        import threading as _threading

        from ceph_tpu.msg.message import MPing
        from ceph_tpu.msg.messenger import Messenger
        recv = Messenger(("osd", 0))
        sender = Messenger(("client", 1))
        peer = Messenger(("osd", 2))
        dispatched = []
        lock = _threading.Lock()

        class Adopting:
            """Dispatcher that ADOPTS each message's throttle budget
            (the osd op_wq hand-off): units stay held until the test
            releases them, exactly like a queued-but-unserved op."""

            def ms_dispatch(self, msg):
                msg._throttle_adopted = True
                with lock:
                    dispatched.append(msg)
                return True

            def ms_handle_reset(self, addr):
                pass

        waits = []
        recv.add_dispatcher_tail(Adopting())
        recv.enable_dispatch_throttle(1, 1 << 20,
                                      wait_cb=waits.append)
        recv.start()
        sender.start()
        peer.start()
        try:
            for i in range(3):
                sender.send_message(MPing(stamp=float(i)),
                                    recv.my_addr)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not dispatched:
                time.sleep(0.01)
            time.sleep(1.0)   # give over-budget messages time to NOT
            #                   arrive
            with lock:
                assert len(dispatched) == 1, \
                    "cap=1 but %d messages dispatched" \
                    % len(dispatched)
                held = dispatched[0]
            # a non-client peer bypasses the client throttle even
            # while the budget is exhausted
            peer.send_message(MPing(stamp=99.0), recv.my_addr)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with lock:
                    if any(m.from_name == ("osd", 2)
                           for m in dispatched):
                        break
                time.sleep(0.01)
            with lock:
                assert any(m.from_name == ("osd", 2)
                           for m in dispatched), \
                    "osd peer was wrongly throttled"
                before = len(dispatched)
            # releasing the adopted budget admits the next client msg
            held.throttle_release()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                with lock:
                    if len(dispatched) > before:
                        break
                time.sleep(0.01)
            with lock:
                client_msgs = [m for m in dispatched
                               if m.from_name == ("client", 1)]
                assert len(client_msgs) == 2, \
                    "release did not admit the queued client message"
            # the admitted message waited measurably: the wait
            # callback (the l_osd_throttle_wait perf lane) fired
            assert waits and max(waits) > 0.5, waits
        finally:
            sender.shutdown()
            peer.shutdown()
            recv.shutdown()


@pytest.mark.slow
class TestBackfillStormLatency:
    """Reservation-throttled recovery must not make client tail
    latency WORSE than unthrottled recovery during a backfill storm
    (the bench --thrash artifact hard-gates the same comparison)."""

    def _storm_leg(self, conf_extra: dict) -> float:
        conf = dict(FAST)
        conf.update(conf_extra)
        cluster = MiniCluster(num_mons=1, num_osds=4,
                              conf_overrides=conf).start()
        lat: list[float] = []
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "storm", size=2,
                                           pg_num=8)
            ioctx = client.open_ioctx("storm")
            for i in range(40):
                ioctx.write_full("s%d" % i, payload_for(i))
            # out->in bounce remaps PGs both ways: a genuine backfill
            # storm competing with the foreground writes below
            client.mon_command({"prefix": "osd out", "id": 3})
            t_end = time.monotonic() + 12
            i, flipped = 0, False
            while time.monotonic() < t_end:
                t0 = time.monotonic()
                try:
                    ioctx.write_full("lat-%d" % i, payload_for(i),
                                     timeout=30.0)
                    lat.append(time.monotonic() - t0)
                except Exception:
                    pass
                if not flipped and i >= 20:
                    client.mon_command({"prefix": "osd in", "id": 3})
                    flipped = True
                i += 1
        finally:
            cluster.stop()
        assert len(lat) >= 20, "storm leg starved: %d writes" % len(lat)
        lat.sort()
        return lat[min(len(lat) - 1, int(len(lat) * 0.99))]

    def test_reservation_throttling_p99_not_worse(self):
        p99_on = self._storm_leg({"osd_max_backfills": 1,
                                  "osd_recovery_max_active": 1,
                                  "osd_recovery_sleep": 0.01})
        p99_off = self._storm_leg({"osd_max_backfills": 64,
                                   "osd_recovery_max_active": 64})
        # 1.5x headroom absorbs shared-CI noise; the regression this
        # guards against (throttling ADDING tail latency) is way past
        # that
        assert p99_on <= p99_off * 1.5, \
            "throttled p99 %.3fs vs unthrottled %.3fs" \
            % (p99_on, p99_off)
