"""PGLog authoritative merge semantics (PGLog::merge_log scenarios)."""

from __future__ import annotations

from ceph_tpu.osd.pg_log import LogEntry, PGLog


def E(epoch, version, oid, kind="modify", prior=0):
    return LogEntry(epoch=epoch, version=version, oid=oid, kind=kind,
                    prior_version=prior)


def make_log(*entries):
    log = PGLog()
    for e in entries:
        log.append(e)
    return log


class TestMerge:
    def test_contiguous_extension(self):
        log = make_log(E(1, 1, "a"), E(1, 2, "b"))
        updates, _ = log.merge([E(1, 3, "c"), E(2, 4, "a")], (2, 4))
        assert updates == {"c": 3, "a": 4}
        assert log.head == (2, 4)
        assert [e.ev for e in log.entries] == [(1, 1), (1, 2), (1, 3),
                                               (2, 4)]

    def test_authoritative_delete(self):
        log = make_log(E(1, 1, "a"))
        updates, _ = log.merge([E(2, 2, "a", kind="delete")], (2, 2))
        assert updates == {"a": 0}

    def test_divergent_create_removed(self):
        """A create acked by nobody (dead-interval write) is undone."""
        log = make_log(E(1, 1, "a"), E(1, 2, "b"),
                       E(2, 3, "x", prior=0))
        auth = [E(1, 1, "a"), E(1, 2, "b"), E(3, 3, "y")]
        updates, _ = log.merge(auth, (3, 3))
        assert updates == {"x": 0, "y": 3}
        assert log.head == (3, 3)
        assert all(e.oid != "x" for e in log.entries)

    def test_divergent_modify_reverts_to_auth_version(self):
        log = make_log(E(1, 1, "a"), E(2, 2, "a", prior=1))
        auth = [E(1, 1, "a"), E(3, 2, "b")]
        updates, _ = log.merge(auth, (3, 2))
        assert updates == {"a": 1, "b": 2}

    def test_divergent_delete_resurrects(self):
        """A divergent DELETE (removed in a dead interval) reverts to
        the authoritative object."""
        log = make_log(E(1, 1, "a"),
                       E(2, 2, "a", kind="delete", prior=1))
        auth = [E(1, 1, "a"), E(3, 2, "c")]
        updates, _ = log.merge(auth, (3, 2))
        assert updates == {"a": 1, "c": 2}

    def test_same_version_fork_detected_by_epoch(self):
        """Two primaries minted version 2 in different epochs: the
        losing fork's entry must be rolled back even though the bare
        version numbers collide."""
        log = make_log(E(1, 1, "a"), E(2, 2, "mine", prior=0))
        auth = [E(1, 1, "a"), E(3, 2, "theirs")]
        updates, _ = log.merge(auth, (3, 2))
        assert updates == {"mine": 0, "theirs": 2}

    def test_rewind_empty_segment(self):
        """Authoritative head BEHIND ours with an empty delta: entries
        past auth_head are divergent."""
        log = make_log(E(1, 1, "a"), E(2, 2, "z", prior=0))
        updates, _ = log.merge([], (1, 1))
        assert updates == {"z": 0}
        assert log.head == (1, 1)

    def test_merge_into_empty_log(self):
        log = PGLog()
        updates, _ = log.merge([E(1, 1, "a"), E(1, 2, "b", kind="delete")],
                            (1, 2))
        assert updates == {"a": 1, "b": 0}
        assert log.head == (1, 2)

    def test_noop_merge(self):
        log = make_log(E(1, 1, "a"))
        assert log.merge([], (1, 1)) == ({}, set())
        assert log.head == (1, 1)

    def test_divergent_then_recreate_in_auth(self):
        """Divergent entry for an oid the auth chain later recreates:
        the auth version wins."""
        log = make_log(E(1, 1, "a"), E(2, 2, "a", prior=1))
        auth = [E(1, 1, "a"), E(3, 2, "a", kind="delete"),
                E(3, 3, "a")]
        updates, _ = log.merge(auth, (3, 3))
        assert updates == {"a": 3}


class TestHelpers:
    def test_entries_since_and_overlap(self):
        log = make_log(E(1, 1, "a"), E(1, 2, "b"), E(2, 3, "c"))
        assert [e.oid for e in log.entries_since((1, 1))] == ["b", "c"]
        assert log.overlaps((1, 2))
        assert log.overlaps((0, 0))
        assert not log.overlaps((9, 9)) or log.head == (9, 9)

    def test_dump_load_roundtrip(self):
        log = make_log(E(1, 1, "a"), E(2, 2, "b", kind="delete",
                                       prior=1))
        log2 = PGLog()
        log2.load(log.dump())
        assert log2.dump() == log.dump()
        assert log2.head == log.head

    def test_trim_moves_tail(self):
        log = PGLog()
        log.CAP = 10
        for i in range(1, 25):
            log.append(E(1, i, "o%d" % i))
        assert len(log.entries) == 10
        assert log.tail == (1, 15)
        assert not log.overlaps((1, 3))


class TestChainedDivergence:
    def test_chained_divergent_entries_revert_to_earliest_prior(self):
        """Two divergent writes to one oid: the revert target is the
        EARLIEST divergent entry's prior_version — later priors are
        divergent versions nobody can serve."""
        log = make_log(E(1, 4, "o"), E(2, 5, "o", prior=4),
                       E(2, 6, "o", prior=5))
        auth = [E(1, 4, "o"), E(3, 5, "x")]
        updates, divergent = log.merge(auth, (3, 5))
        assert updates["o"] == 4          # not 5
        assert "o" in divergent
        assert updates["x"] == 5

    def test_trim_reports_dropped_entries(self):
        log = PGLog()
        log.CAP = 3
        dropped = []
        for i in range(1, 6):
            dropped.extend(log.append(E(1, i, "o%d" % i)))
        assert [e.version for e in dropped] == [1, 2]
        assert len(log.entries) == 3
