"""Device-runtime profiler (common/profiler.py) + its health plumbing.

Unit coverage for the DeviceProfiler registry (shape-signature
compile/hit accounting, recompile-storm detection, the device-memory
ledger) and cluster round trips for the two health checks it feeds:
DEVICE_RECOMPILE_STORM (shape churn -> MPGStats -> mon) and
DEVICE_MEM_NEARFULL (HBM tier occupancy over osd_hbm_nearfull_ratio).
Also the perf-schema drift walk: every counter a daemon dumps at
runtime must be declared in `perf schema` with a valid kind.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.common.profiler import DeviceProfiler, PROFILER
from ceph_tpu.common.perf_counters import (
    U64, U64_COUNTER, TIME, TIME_AVG, U64_AVG, HISTOGRAM)

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}


class TestWrapJit:
    def test_fresh_signature_is_compile_then_cache_hits(self):
        p = DeviceProfiler()
        calls = []
        fn = p.wrap_jit("t.k", lambda x: calls.append(x) or x.sum())
        a = np.zeros((2, 4), np.uint8)
        fn(a)
        fn(a)
        fn(np.ones((2, 4), np.uint8))   # same shape+dtype: still a hit
        k = p.dump()["kernels"]["t.k"]
        assert k["compiles"] == 1
        assert k["cache_hits"] == 2
        assert k["num_signatures"] == 1
        assert k["compile_wall_s"] >= 0
        assert len(calls) == 3          # the wrapped fn always runs

    def test_distinct_shapes_are_distinct_signatures(self):
        p = DeviceProfiler()
        fn = p.wrap_jit("t.k", lambda x: x)
        for n in (1, 2, 3):
            fn(np.zeros(n, np.uint8))
        fn(np.zeros(2, np.uint8))       # revisit: hit, not compile
        k = p.dump()["kernels"]["t.k"]
        assert k["compiles"] == 3
        assert k["cache_hits"] == 1
        assert k["num_signatures"] == 3

    def test_scalars_and_kwargs_participate_in_signature(self):
        p = DeviceProfiler()
        fn = p.wrap_jit("t.k", lambda x, n=0: x)
        a = np.zeros(4, np.uint8)
        fn(a, n=1)
        fn(a, n=2)                      # static arg changed: recompile
        fn(a, n=1)                      # seen: hit
        k = p.dump()["kernels"]["t.k"]
        assert k["compiles"] == 2 and k["cache_hits"] == 1

    def test_disabled_profiler_records_nothing(self):
        p = DeviceProfiler()
        p.enabled = False
        fn = p.wrap_jit("t.k", lambda x: x * 2)
        out = fn(np.full(3, 7, np.uint8))
        assert (out == 14).all()        # transparent passthrough
        assert p.dump()["kernels"] == {}
        p.mem_add("hbm_tier", 100)
        assert p.mem_dump()["total_bytes"] == 0


class TestStormDetector:
    def test_storm_trips_at_threshold_within_window(self):
        p = DeviceProfiler(recompile_window=60.0, recompile_threshold=3)
        fn = p.wrap_jit("churny", lambda x: x)
        for n in range(1, 5):
            fn(np.zeros(n, np.uint8))
        rep = p.storm_report()
        assert rep["storming"] and rep["kernel"] == "churny"
        assert rep["count"] == 4
        assert p.storm_count() == 4

    def test_calm_kernel_below_threshold(self):
        p = DeviceProfiler(recompile_threshold=10)
        fn = p.wrap_jit("calm", lambda x: x)
        for n in range(1, 4):
            fn(np.zeros(n, np.uint8))
        assert not p.storm_report()["storming"]
        assert p.storm_count() == 0

    def test_per_kernel_thresholding(self):
        """The storm verdict names the WORST kernel; a stable kernel's
        single compile never pools with another kernel's churn."""
        p = DeviceProfiler(recompile_threshold=3)
        churn = p.wrap_jit("churny", lambda x: x)
        stable = p.wrap_jit("stable", lambda x: x)
        stable(np.zeros(8, np.uint8))
        for n in range(1, 5):
            churn(np.zeros(n, np.uint8))
        rep = p.storm_report()
        assert rep["kernel"] == "churny" and rep["count"] == 4

    def test_events_outside_window_expire(self):
        p = DeviceProfiler(recompile_window=0.5, recompile_threshold=2)
        p.record_compile("old", ("sig",), 0.0)
        import time
        rep = p.storm_report(now=time.monotonic() + 1.0)
        assert rep["count"] == 0 and not rep["storming"]

    def test_reset_clears_registry_and_events(self):
        p = DeviceProfiler(recompile_threshold=1)
        fn = p.wrap_jit("k", lambda x: x)
        fn(np.zeros(2, np.uint8))
        assert p.storm_count() >= 1
        p.reset()
        assert p.storm_count() == 0
        assert p.dump()["kernels"] == {}


class TestMemLedger:
    def test_add_sub_and_high_watermark(self):
        p = DeviceProfiler()
        p.mem_add("staging_ring", 100)
        p.mem_add("staging_ring", 50)
        p.mem_sub("staging_ring", 120)
        d = p.mem_dump()["staging_ring"]
        assert d["bytes"] == 30 and d["high_watermark"] == 150

    def test_sub_floors_at_zero(self):
        p = DeviceProfiler()
        p.mem_add("donated_buffers", 10)
        p.mem_sub("donated_buffers", 999)
        assert p.mem_dump()["donated_buffers"]["bytes"] == 0

    def test_set_is_a_gauge(self):
        p = DeviceProfiler()
        p.mem_set("decode_tables", 400)
        p.mem_set("decode_tables", 100)
        d = p.mem_dump()["decode_tables"]
        assert d["bytes"] == 100 and d["high_watermark"] == 400

    def test_total_sums_categories(self):
        p = DeviceProfiler()
        p.mem_set("hbm_tier", 70)
        p.mem_set("decode_tables", 30)
        assert p.mem_dump()["total_bytes"] == 100

    def test_reset_keeps_live_bytes_rebases_watermark(self):
        """Live bytes are real residency, not statistics: `profile
        reset` must not zero them, only rebase the watermark."""
        p = DeviceProfiler()
        p.mem_add("hbm_tier", 500)
        p.mem_sub("hbm_tier", 300)
        p.reset()
        d = p.mem_dump()["hbm_tier"]
        assert d["bytes"] == 200 and d["high_watermark"] == 200


class TestDumpShape:
    def test_dump_carries_every_section(self):
        p = DeviceProfiler()
        fn = p.wrap_jit("k", lambda x: x)
        fn(np.zeros(2, np.uint8))
        p.mem_add("hbm_tier", 1)
        doc = p.dump()
        assert doc["enabled"] is True
        assert set(doc) == {"enabled", "kernels", "recompile_storm",
                            "memory"}
        sig = doc["kernels"]["k"]["signatures"][0]
        assert {"sig", "compiles", "compile_wall_s",
                "cache_hits"} <= set(sig)


def _health_checks(client):
    res, _, data = client.mon_command({"prefix": "health"})
    assert res == 0
    return data["checks"]


class TestRecompileStormHealth:
    def test_shape_churn_raises_and_clears_storm_check(self):
        """Forced shape churn on a registered kernel trips
        DEVICE_RECOMPILE_STORM in `ceph health` via the MPGStats feed,
        and a calm window (profile reset) retires it."""
        from .cluster_util import MiniCluster, wait_until
        conf = dict(FAST, osd_profiler_recompile_threshold=4,
                    osd_profiler_recompile_window=60.0)
        prev = (PROFILER.enabled, PROFILER.recompile_window,
                PROFILER.recompile_threshold)
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            # clean slate: compiles from earlier tests in this process
            # must not pre-trip the window
            PROFILER.reset()
            churn = PROFILER.wrap_jit("test.storm_kernel", lambda x: x)
            for n in range(1, 8):       # 7 fresh shapes >> threshold 4
                churn(np.zeros(n, np.uint8))
            assert PROFILER.storm_count() >= 4
            assert wait_until(
                lambda: "DEVICE_RECOMPILE_STORM"
                in _health_checks(client), timeout=20)
            check = _health_checks(client)["DEVICE_RECOMPILE_STORM"]
            assert check["severity"] == "warning"
            assert any("osd." in d and "recompiled" in d
                       for d in check["detail"])
            # calm window: reset the registry; the osds re-report 0 and
            # the mon retires the check
            PROFILER.reset()
            assert wait_until(
                lambda: "DEVICE_RECOMPILE_STORM"
                not in _health_checks(client), timeout=20)
        finally:
            PROFILER.reset()
            (PROFILER.enabled, PROFILER.recompile_window,
             PROFILER.recompile_threshold) = prev
            cluster.stop()


class TestMemNearfullHealth:
    def test_hbm_tier_pressure_raises_and_clears_nearfull(self):
        """Filling the HBM chunk tier past osd_hbm_nearfull_ratio
        raises DEVICE_MEM_NEARFULL; dropping residency clears it."""
        from .cluster_util import MiniCluster, wait_until
        conf = dict(FAST, osd_hbm_tier_capacity=8)
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            tier = cluster.osds[0].hbm_tier
            if tier is None:
                pytest.skip("hbm tier unavailable in this environment")
            data = np.zeros((1, 2, 128), np.uint8)
            parity = np.zeros((1, 1, 128), np.uint8)
            for i in range(8):
                tier.adopt_encode("nf-%d" % i, data, parity, None)
            assert tier.occupancy() >= 0.85
            assert wait_until(
                lambda: "DEVICE_MEM_NEARFULL"
                in _health_checks(client), timeout=20)
            check = _health_checks(client)["DEVICE_MEM_NEARFULL"]
            assert check["severity"] == "warning"
            assert any("osd.0" in d and "full" in d
                       for d in check["detail"])
            for i in range(8):
                tier.drop("nf-%d" % i)
            assert tier.occupancy() == 0.0
            assert wait_until(
                lambda: "DEVICE_MEM_NEARFULL"
                not in _health_checks(client), timeout=20)
        finally:
            cluster.stop()


class TestPerfSchemaDrift:
    VALID_KINDS = {U64, U64_COUNTER, TIME, TIME_AVG, U64_AVG,
                   HISTOGRAM}

    def test_every_runtime_counter_is_in_schema_with_valid_kind(self):
        """Walk every PerfCounters logger a live OSD dumps after real
        IO: each counter must appear in `perf schema` under the same
        logger with one of the declared kinds — a counter registered
        outside the builder (or a kind typo) fails here instead of
        silently rendering wrong in the mgr exposition."""
        from .cluster_util import MiniCluster
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "schemadrift",
                                           size=2, pg_num=4)
            ioctx = client.open_ioctx("schemadrift")
            for i in range(4):
                ioctx.write_full("o%d" % i, b"x" * 4096)
                assert ioctx.read("o%d" % i) == b"x" * 4096
            for osd_id, osd in cluster.osds.items():
                dump = osd.ctx.perf.perf_dump()
                schema = osd.ctx.perf.perf_schema()
                assert dump, "osd.%d dumps no loggers" % osd_id
                for logger, counters in dump.items():
                    assert logger in schema, logger
                    for name in counters:
                        assert name in schema[logger], (logger, name)
                        kind = schema[logger][name]["type"]
                        assert kind in self.VALID_KINDS, \
                            (logger, name, kind)
                # the new stage counters are part of the walk
                tpu = [lg for lg in dump if "tpu" in lg]
                if tpu:
                    assert any(
                        "l_tpu_stage_h2d_busy" in dump[lg]
                        for lg in tpu)
                # the perf-query counters registered through the same
                # builder are part of the walk too
                osd_group = dump.get("osd", {})
                for pq_ctr in ("l_osd_pq_queries", "l_osd_pq_keys",
                               "l_osd_pq_samples",
                               "l_osd_pq_evictions"):
                    assert pq_ctr in osd_group, pq_ctr
                    assert pq_ctr in schema["osd"], pq_ctr
                # the tail-sampler lanes ride the same builder
                for tail_ctr in ("l_osd_trace_tail_kept_slo",
                                 "l_osd_trace_tail_kept_error",
                                 "l_osd_trace_tail_kept_reservoir",
                                 "l_osd_trace_tail_dropped",
                                 "l_osd_trace_tail_shipped_spans",
                                 "l_osd_trace_tail_expired"):
                    assert tail_ctr in osd_group, tail_ctr
                    assert tail_ctr in schema["osd"], tail_ctr
        finally:
            cluster.stop()

    def test_mgr_trace_counters_in_schema(self):
        """The mgr's trace-store lanes (l_mgr_trace_*) must live in
        the daemon's own 'mgr' PerfCounters group — a second group
        with the same name would silently REPLACE it in the
        collection — and carry schema-valid kinds."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr import MgrDaemon
        mgr = MgrDaemon({}, ctx=Context(name="mgr.drift"))
        try:
            dump = mgr.ctx.perf.perf_dump()
            schema = mgr.ctx.perf.perf_schema()
            group = dump.get("mgr", {})
            for ctr in ("l_mgr_trace_fragments", "l_mgr_trace_spans",
                        "l_mgr_trace_bytes", "l_mgr_trace_stored",
                        "l_mgr_trace_evicted"):
                assert ctr in group, ctr
                assert ctr in schema["mgr"], ctr
                assert schema["mgr"][ctr]["type"] in \
                    self.VALID_KINDS, ctr
        finally:
            mgr.shutdown()
