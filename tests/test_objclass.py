"""Object-class (cls) tests.

Models the reference's cls coverage (src/test/cls_hello,
src/test/cls_lock, src/test/cls_refcount): method dispatch via the
exec op against a live cluster, RD/WR flag enforcement, built-in class
semantics, and the EC-pool EOPNOTSUPP rule
(ecbackend.rst:79-83).
"""

from ceph_tpu import encoding

import pytest

from ceph_tpu.client.rados import RadosError
from ceph_tpu.osd.objclass import (CLS_METHOD_RD, CLS_METHOD_WR,
                                   ClassHandler)

from .cluster_util import MiniCluster

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


@pytest.fixture(scope="module")
def ctx():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    client = cluster.client()
    cluster.create_replicated_pool(client, "clspool", size=2, pg_num=4)
    ioctx = client.open_ioctx("clspool")
    yield cluster, client, ioctx
    cluster.stop()


class TestRegistry:
    def test_register_and_lookup(self):
        h = ClassHandler()
        c = h.register_class("custom")
        c.register_method("m", CLS_METHOD_RD, lambda hctx, d: (0, d))
        assert h.get_method("custom", "m").flags == CLS_METHOD_RD
        assert h.get_method("custom", "nope") is None
        assert h.get_method("nope", "m") is None
        with pytest.raises(ValueError):
            c.register_method("m", CLS_METHOD_RD, lambda hctx, d: (0, d))

    def test_builtins_present(self):
        h = ClassHandler.instance()
        for cls_name, method in (("hello", "say_hello"),
                                 ("lock", "lock"),
                                 ("refcount", "get")):
            assert h.get_method(cls_name, method) is not None


class TestHello:
    def test_say_hello(self, ctx):
        _, _, ioctx = ctx
        assert ioctx.exec("greet", "hello", "say_hello") == b"Hello, world!"
        assert ioctx.exec("greet", "hello", "say_hello",
                          b"ceph") == b"Hello, ceph!"

    def test_record_hello_writes_and_eexist(self, ctx):
        _, _, ioctx = ctx
        ioctx.exec("note", "hello", "record_hello", b"first")
        assert ioctx.get_xattr("note", "hello.greeted") == b"first"
        with pytest.raises(RadosError) as ei:
            ioctx.exec("note", "hello", "record_hello", b"second")
        assert ei.value.errno == 17  # EEXIST

    def test_unknown_class_or_method(self, ctx):
        _, _, ioctx = ctx
        for cls_name, method in (("nope", "x"), ("hello", "nope")):
            with pytest.raises(RadosError) as ei:
                ioctx.exec("greet", cls_name, method)
            assert ei.value.errno == 95  # EOPNOTSUPP


class TestLock:
    def test_exclusive_lock_cycle(self, ctx):
        _, _, ioctx = ctx
        req = {"name": "l1", "cookie": "c1", "type": "exclusive"}
        ioctx.exec("locked", "lock", "lock", encoding.encode_any(req))
        # a second locker is refused
        with pytest.raises(RadosError) as ei:
            ioctx.exec("locked", "lock", "lock", encoding.encode_any(
                {"name": "l1", "cookie": "c2", "type": "exclusive"}))
        assert ei.value.errno == 16  # EBUSY
        info = encoding.decode_any(ioctx.exec(
            "locked", "lock", "get_info", encoding.encode_any({"name": "l1"})))
        assert list(info["lockers"]) == ["c1"]
        ioctx.exec("locked", "lock", "unlock",
                   encoding.encode_any({"name": "l1", "cookie": "c1"}))
        # now c2 can take it
        ioctx.exec("locked", "lock", "lock", encoding.encode_any(
            {"name": "l1", "cookie": "c2", "type": "exclusive"}))

    def test_shared_lock(self, ctx):
        _, _, ioctx = ctx
        for cookie in ("s1", "s2"):
            ioctx.exec("shared", "lock", "lock", encoding.encode_any(
                {"name": "l", "cookie": cookie, "type": "shared"}))
        info = encoding.decode_any(ioctx.exec(
            "shared", "lock", "get_info", encoding.encode_any({"name": "l"})))
        assert sorted(info["lockers"]) == ["s1", "s2"]
        # exclusive is refused while shared lockers hold it
        with pytest.raises(RadosError):
            ioctx.exec("shared", "lock", "lock", encoding.encode_any(
                {"name": "l", "cookie": "x", "type": "exclusive"}))

    def test_unlock_wrong_cookie_enoent(self, ctx):
        _, _, ioctx = ctx
        with pytest.raises(RadosError) as ei:
            ioctx.exec("locked", "lock", "unlock",
                       encoding.encode_any({"name": "l1", "cookie": "ghost"}))
        assert ei.value.errno == 2


class TestRefcount:
    def test_get_put_and_final_removal(self, ctx):
        _, _, ioctx = ctx
        ioctx.write_full("counted", b"payload")
        ioctx.exec("counted", "refcount", "get", b"tagA")
        ioctx.exec("counted", "refcount", "get", b"tagB")
        refs = encoding.decode_any(ioctx.exec("counted", "refcount", "read"))
        assert refs == ["tagA", "tagB"]
        ioctx.exec("counted", "refcount", "put", b"tagA")
        assert ioctx.read("counted") == b"payload"   # still referenced
        ioctx.exec("counted", "refcount", "put", b"tagB")
        # last ref dropped -> the object is gone
        with pytest.raises(RadosError) as ei:
            ioctx.stat("counted")
        assert ei.value.errno == 2


class TestECPoolRefusal:
    def test_exec_on_ec_pool_eopnotsupp(self, ctx):
        cluster, client, _ = ctx
        cluster.create_ec_pool(client, "clsec",
                               {"plugin": "jerasure",
                                "technique": "reed_sol_van",
                                "k": "2", "m": "1"}, pg_num=4)
        ec_io = client.open_ioctx("clsec")
        ec_io.write_full("obj", b"data")
        with pytest.raises(RadosError) as ei:
            ec_io.exec("obj", "hello", "say_hello")
        assert ei.value.errno == 95  # EOPNOTSUPP (ecbackend.rst:79-83)
