"""objectstore-tool: offline PG export/import/remove surgery.

The VERDICT round-1 'done' gate: kill an OSD, surgically export a PG
from its store, import it on another OSD, and the cluster recovers —
the ceph-objectstore-tool disaster-recovery workflow."""

from __future__ import annotations

import pytest

from ceph_tpu.store.block_store import BlockStore
from ceph_tpu.store.file_store import FileStore
from ceph_tpu.store.object_store import Transaction
from ceph_tpu.tools import objectstore_tool as ost

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}


def seeded_store(path, kind=FileStore):
    st = kind(str(path))
    st.mount()
    txn = Transaction()
    cid = ("pg", "1.0", -1)
    txn.create_collection(cid)
    txn.write(cid, "alpha", 0, b"alpha-bytes")
    txn.setattr(cid, "alpha", "_v", b"3")
    txn.omap_setkeys(cid, "alpha", {"k": b"v"})
    txn.write(cid, "beta", 0, b"beta-bytes")
    st.queue_transaction(txn)
    return st


class TestOffline:
    def test_list_pgs_and_objects(self, tmp_path):
        st = seeded_store(tmp_path / "osd")
        assert ost.list_pgs(st) == ["1.0"]
        objs = [oid for _, oid in ost.list_objects(st, "1.0")]
        assert set(objs) == {"alpha", "beta"}
        st.umount()

    @pytest.mark.parametrize("kind", [FileStore, BlockStore])
    def test_export_import_roundtrip(self, tmp_path, kind):
        src = seeded_store(tmp_path / "src", kind)
        blob = ost.export_pg(src, "1.0")
        src.umount()

        dst = kind(str(tmp_path / "dst"))
        dst.mount()
        assert ost.import_pg(dst, blob) == "1.0"
        cid = ("pg", "1.0", -1)
        assert dst.read(cid, "alpha") == b"alpha-bytes"
        assert dst.getattr(cid, "alpha", "_v") == b"3"
        assert dst.omap_get(cid, "alpha") == {"k": b"v"}
        assert dst.read(cid, "beta") == b"beta-bytes"
        # refuses to clobber without force
        with pytest.raises(SystemExit):
            ost.import_pg(dst, blob)
        ost.import_pg(dst, blob, force=True)
        dst.umount()

    def test_remove_pg(self, tmp_path):
        st = seeded_store(tmp_path / "osd")
        assert ost.remove_pg(st, "1.0") == 1
        assert ost.list_pgs(st) == []
        st.umount()

    def test_cli_surface(self, tmp_path, capsys):
        seeded_store(tmp_path / "osd").umount()
        assert ost.main(["--data-path", str(tmp_path / "osd"),
                         "--op", "list-pgs"]) == 0
        assert "1.0" in capsys.readouterr().out
        out_file = tmp_path / "export.bin"
        assert ost.main(["--data-path", str(tmp_path / "osd"),
                         "--op", "export", "--pgid", "1.0",
                         "--file", str(out_file)]) == 0
        assert out_file.stat().st_size > 0
        got = tmp_path / "alpha.bin"
        assert ost.main(["--data-path", str(tmp_path / "osd"),
                         "--op", "get-bytes", "--pgid", "1.0",
                         "--oid", "alpha", "--file", str(got)]) == 0
        assert got.read_bytes() == b"alpha-bytes"


class TestDisasterRecovery:
    def test_export_dead_osd_import_elsewhere_cluster_recovers(
            self, tmp_path):
        """The headline workflow: OSD dies for good; its PG copy is
        surgically exported offline and imported into a replacement
        OSD's store; the cluster serves the data again."""
        cluster = MiniCluster(num_mons=1, num_osds=0,
                              conf_overrides=FAST)
        from ceph_tpu.common.context import Context
        from ceph_tpu.mon.monitor import Monitor
        for rank in cluster.monmap:
            mon = Monitor(rank, cluster.monmap,
                          Context(FAST, name="mon.%d" % rank))
            mon.init()
            cluster.mons.append(mon)
        assert wait_until(lambda: any(m.is_leader()
                                      for m in cluster.mons))
        stores = {}
        try:
            for osd_id in range(3):
                path = tmp_path / ("osd.%d" % osd_id)
                path.mkdir()
                stores[osd_id] = FileStore(str(path),
                                           journal_sync=False)
                stores[osd_id].mount()
                cluster.start_osd(osd_id, store=stores[osd_id])
            cluster.num_osds = 3
            assert wait_until(cluster.all_osds_up, timeout=15)
            client = cluster.client()
            cluster.create_replicated_pool(client, "dr", size=2,
                                           pg_num=1)
            ioctx = client.open_ioctx("dr")
            ioctx.write_full("precious", b"must survive surgery")

            # find a PG copy and its host; kill that OSD permanently
            holder = next(o for o in range(3)
                          if ost.list_pgs(stores[o]))
            pgid = ost.list_pgs(stores[holder])[0]
            cluster.stop_osd(holder)
            stores[holder].umount() if stores[holder].mounted else None

            # offline surgery: export from the dead OSD's directory,
            # import into a brand-new OSD's store
            dead = ost.open_store(str(tmp_path / ("osd.%d" % holder)))
            blob = ost.export_pg(dead, pgid)
            dead.umount()
            newpath = tmp_path / "osd.9"
            newpath.mkdir()
            surgeon = ost.open_store(str(newpath))
            ost.import_pg(surgeon, blob)
            surgeon.umount()

            # boot the replacement OSD on the repaired store
            replacement = FileStore(str(newpath), journal_sync=False)
            replacement.mount()
            cluster.start_osd(9, store=replacement)
            assert wait_until(
                lambda: cluster.leader().osdmon.osdmap.is_up(9),
                timeout=15)
            assert ioctx.read("precious") == b"must survive surgery"
            # the imported copy really participates: the replacement's
            # store holds the bytes
            found = any(
                b"must survive surgery" in bytes(
                    replacement.read(cid, oid))
                for cid, oid in ost.list_objects(replacement)
                if not str(oid).startswith("__pg_"))
            assert found
        finally:
            cluster.stop()


class TestForceClobbers:
    def test_force_import_does_not_resurrect_deleted_objects(
            self, tmp_path):
        st = seeded_store(tmp_path / "osd")
        blob = ost.export_pg(st, "1.0")
        # an object deleted AFTER the export must not survive a forced
        # re-import (clobber, not merge)
        cid = ("pg", "1.0", -1)
        txn = Transaction()
        txn.write(cid, "post-export-ghost", 0, b"stale")
        st.queue_transaction(txn)
        ost.import_pg(st, blob, force=True)
        assert "post-export-ghost" not in st.list_objects(cid)
        assert st.read(cid, "alpha") == b"alpha-bytes"
        st.umount()


class TestSetBytesPreservesMeta:
    def test_set_bytes_keeps_xattrs_and_omap(self, tmp_path, capsys):
        seeded_store(tmp_path / "osd").umount()
        newdata = tmp_path / "new.bin"
        newdata.write_bytes(b"repaired payload")
        assert ost.main(["--data-path", str(tmp_path / "osd"),
                         "--op", "set-bytes", "--pgid", "1.0",
                         "--oid", "alpha",
                         "--file", str(newdata)]) == 0
        st = ost.open_store(str(tmp_path / "osd"))
        cid = ("pg", "1.0", -1)
        assert st.read(cid, "alpha") == b"repaired payload"
        assert st.getattr(cid, "alpha", "_v") == b"3"
        assert st.omap_get(cid, "alpha") == {"k": b"v"}
        st.umount()

    def test_missing_oid_errors_cleanly(self, tmp_path):
        seeded_store(tmp_path / "osd").umount()
        with pytest.raises(SystemExit):
            ost.main(["--data-path", str(tmp_path / "osd"),
                      "--op", "get-bytes", "--pgid", "1.0",
                      "--oid", "typo", "--file", str(tmp_path / "x")])
