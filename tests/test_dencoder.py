"""dencoder + committed golden corpus (the readable.sh contract):
today's code must keep decoding yesterday's bytes."""

from __future__ import annotations

import os

from ceph_tpu.tools import dencoder

CORPUS = os.path.join(os.path.dirname(__file__), "corpus")


class TestDencoder:
    def test_committed_corpus_still_readable(self):
        failures = dencoder.check_corpus(CORPUS)
        assert not failures, failures

    def test_corpus_covers_message_catalog(self):
        from ceph_tpu.msg import message as m
        have = {f[:-4] for f in os.listdir(CORPUS) if f.endswith(".bin")}
        for name in m.__all__:
            if name == "Message":
                continue
            assert "msg." + name in have, name

    def test_regenerated_corpus_matches_committed(self):
        """Encodings are deterministic: re-encoding the canonical
        samples must reproduce the committed bytes (catches silent
        format drift in either direction)."""
        from ceph_tpu import encoding
        for name, value in dencoder.corpus_samples().items():
            path = os.path.join(CORPUS, name.replace("/", "_") + ".bin")
            with open(path, "rb") as f:
                committed = f.read()
            assert encoding.encode_any(value) == committed, name

    def test_dump_is_deterministic(self):
        samples = dencoder.corpus_samples()
        for name, value in samples.items():
            assert dencoder.dump(value) == dencoder.dump(value), name

    def test_cli_list_and_decode(self, tmp_path, capsys):
        assert dencoder.main(["list_types"]) == 0
        out = capsys.readouterr().out
        assert "osd.OSDMap" in out and "msg.MOSDOp" in out
        blob_path = os.path.join(CORPUS, "osd.PGID.bin")
        assert dencoder.main(["decode", blob_path]) == 0
        assert "PGID" in capsys.readouterr().out
