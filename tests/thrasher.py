"""Randomized failure injection against a live MiniCluster.

Models the reference's teuthology Thrasher
(qa/tasks/ceph_manager.py:98 — kill_osd :205, revive_osd :426): a
background loop that keeps killing and reviving OSDs (never dipping
below min_in) while a foreground workload runs, so recovery,
re-peering, and degraded IO get exercised under churn instead of in
staged one-shot tests.
"""

from __future__ import annotations

import random
import threading
import time

from .cluster_util import wait_until

__all__ = ["Thrasher"]


class Thrasher:
    def __init__(self, cluster, seed: int = 0, min_in: int = 2,
                 interval: float = 0.5, revive_delay: float = 0.8,
                 partition_prob: float = 0.0,
                 mon_thrash_prob: float = 0.0,
                 device_thrash_prob: float = 0.0,
                 map_churn_prob: float = 0.0,
                 churn_pool: str | None = None):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.min_in = min_in
        self.interval = interval
        self.revive_delay = revive_delay
        self.partition_prob = partition_prob
        self.mon_thrash_prob = mon_thrash_prob
        self.device_thrash_prob = device_thrash_prob
        # map-churn riders (ISSUE 19): out/in storms, reweight sweeps
        # and pool resizes drive osdmap epochs WITHOUT killing daemons
        # — the churn class the incremental-map pipeline exists for.
        # churn_pool names a dedicated pool the resize rider may grow
        # (splits instantiate fresh PGs); None disables resizes.
        self.map_churn_prob = map_churn_prob
        self.churn_pool = churn_pool
        self.reweighted: set[int] = set()     # osds left off weight 1
        self.outed: set[int] = set()          # osds the storm left out
        self.dead: dict[int, object] = {}     # osd_id -> store
        self.dead_devices: set[int] = set()   # injector-killed chips
        self.partitions: set[tuple[int, int]] = set()  # (a, b) pairs
        self.log: list[tuple] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.errors: list[str] = []

    # -- actions (kill_osd / revive_osd) -------------------------------

    def _alive(self) -> list[int]:
        return sorted(set(self.cluster.osds) - set(self.dead))

    def _journal(self, action: str, what: str, **data) -> None:
        """Record the injected fault in the mon's cluster event
        journal, so `ceph events last` interleaves what the thrasher
        DID with how the cluster REACTED (down/out epochs, health
        transitions). Journaling must never change the thrash behavior
        itself, but a failure to journal is a FINDING (a dead event
        path mid-thrash), so it lands in self.errors instead of being
        swallowed."""
        try:
            leader = self.cluster.leader()
            eventmon = getattr(leader, "eventmon", None)
            if eventmon is not None:
                eventmon.submit(
                    "thrash", "thrasher: %s %s" % (action, what),
                    source="thrasher",
                    data=dict(data, action=action))
        except Exception as e:
            self.errors.append("journal(%s %s): %r" % (action, what, e))

    def kill_one(self) -> int | None:
        alive = self._alive()
        if len(alive) <= self.min_in:
            return None
        victim = self.rng.choice(alive)
        store = self.cluster.stop_osd(victim)
        self.dead[victim] = store
        self.log.append(("kill", victim))
        self._journal("kill", "osd.%d" % victim, osd=victim)
        return victim

    def revive_one(self) -> int | None:
        if not self.dead:
            return None
        osd_id = self.rng.choice(sorted(self.dead))
        store = self.dead.pop(osd_id)
        self.cluster.revive_osd(osd_id, store=store)
        # a revived daemon boots with fresh messengers: re-apply any
        # standing partition it is party to, or the blackhole would
        # silently evaporate on the revived side
        for a, b in self.partitions:
            if osd_id in (a, b):
                self._set_blocked(osd_id, b if osd_id == a else a, True)
        # an auto-marked-out osd needs an explicit "in" (ceph_manager
        # revive_osd does the same); a command that keeps failing even
        # with retries is a real finding — record it, don't swallow it
        client = self.cluster.clients[0] if self.cluster.clients else None
        if client is not None:
            for attempt in range(3):
                try:
                    client.mon_command({"prefix": "osd in",
                                        "id": osd_id})
                    break
                except Exception as e:
                    if attempt == 2:
                        self.errors.append(
                            "revive osd.%d: 'osd in' failed: %r"
                            % (osd_id, e))
                    else:
                        time.sleep(0.3)
        self.log.append(("revive", osd_id))
        self._journal("revive", "osd.%d" % osd_id, osd=osd_id)
        return osd_id

    # -- network partitions (blackhole both directions) ----------------

    def _set_blocked(self, victim: int, peer: int, blocked: bool) -> None:
        """(Un)blackhole frames FROM osd.peer on every transport of
        osd.victim (public / cluster / heartbeat)."""
        daemon = self.cluster.osds.get(victim)
        if daemon is None:
            return
        for msgr in (daemon.public_msgr, daemon.cluster_msgr,
                     daemon.hb_msgr):
            if blocked:
                msgr.block_peer(("osd", peer))
            else:
                msgr.unblock_peer(("osd", peer))

    def partition(self, a: int, b: int) -> None:
        """Blackhole osd.a <-> osd.b: each side's messengers kill any
        pipe delivering a frame from the other, so heartbeats stop
        flowing and the peers report each other down (MOSDFailure)
        while both stay mon-reachable — the classic partial-partition
        failure the reference thrashes with iptables DROP rules."""
        self._set_blocked(a, b, True)
        self._set_blocked(b, a, True)
        self.partitions.add((min(a, b), max(a, b)))
        self.log.append(("partition", a, b))
        self._journal("partition", "osd.%d <-> osd.%d" % (a, b),
                      a=a, b=b)

    def heal(self) -> None:
        """Lift every standing partition (both directions); the
        messengers' lossless resend machinery redelivers whatever was
        blackholed once the pipes reconnect."""
        while self.partitions:
            a, b = self.partitions.pop()
            self._set_blocked(a, b, False)
            self._set_blocked(b, a, False)
            self.log.append(("heal", a, b))
            self._journal("heal", "osd.%d <-> osd.%d" % (a, b),
                          a=a, b=b)

    # -- device chaos (rateless mesh fault injector) --------------------

    def _mesh_devices(self) -> int:
        """Chip count of the process-global rateless dispatcher, 0 when
        the mesh path is inactive (single device / disabled)."""
        from ceph_tpu.parallel import rateless
        disp = rateless.get_dispatcher(create=False)
        return len(disp.devices) if disp is not None else 0

    def kill_device(self, idx: int | None = None) -> int | None:
        """Injector-kill one mesh chip: every micro-batch it pulls
        raises DeviceKilled, the dispatcher drains its in-flight work
        back to the queue and blacklists it, and the mesh degrades to
        the survivors (DEVICE_DEGRADED on the mon). Always leaves at
        least one chip alive — an all-dead mesh only has the host
        fallback, which is survival, not the degradation under test."""
        n = self._mesh_devices()
        if n == 0 or len(self.dead_devices) >= n - 1:
            return None
        if idx is None:
            alive = [i for i in range(n) if i not in self.dead_devices]
            idx = self.rng.choice(alive)
        elif idx in self.dead_devices:
            return None
        from ceph_tpu.parallel.rateless import DEVICE_FAULTS
        DEVICE_FAULTS.kill(idx)
        self.dead_devices.add(idx)
        self.log.append(("device_kill", idx))
        self._journal("device kill", "device %d" % idx, device=idx)
        return idx

    def revive_device(self, idx: int | None = None) -> int | None:
        """Lift the injector kill; the chip re-enters through the
        blacklist->probation->canary path, not straight to healthy."""
        if not self.dead_devices:
            return None
        if idx is None:
            idx = self.rng.choice(sorted(self.dead_devices))
        elif idx not in self.dead_devices:
            return None
        from ceph_tpu.parallel.rateless import DEVICE_FAULTS
        DEVICE_FAULTS.revive(idx)
        self.dead_devices.discard(idx)
        self.log.append(("device_revive", idx))
        self._journal("device revive", "device %d" % idx, device=idx)
        return idx

    def stall_device(self, idx: int, ms: float) -> None:
        """Slow one chip without killing it — the straggler case the
        speculative re-dispatch deadline exists for."""
        from ceph_tpu.parallel.rateless import DEVICE_FAULTS
        DEVICE_FAULTS.stall_ms(idx, ms)
        self.log.append(("device_stall", idx, ms))
        self._journal("device stall", "device %d (%.0fms)" % (idx, ms),
                      device=idx, ms=ms)

    # -- map churn (ISSUE 19: epochs without process deaths) -----------

    def _mon_cmd(self, cmd: dict, what: str) -> bool:
        """Issue a mon command through the cluster's first client; a
        rider that cannot reach the mon records a finding instead of
        crashing the thrash loop."""
        client = self.cluster.clients[0] if self.cluster.clients \
            else None
        if client is None:
            return False
        try:
            client.mon_command(cmd)
            return True
        except Exception as e:
            self.errors.append("%s: %r" % (what, e))
            return False

    def out_in_storm(self, count: int | None = None) -> list[int]:
        """Mark a random batch of up OSDs OUT in one burst, then back
        IN: two epoch waves of pure placement churn (pg_temp, remap,
        backfill scheduling) with every daemon still alive."""
        alive = [o for o in self._alive() if o not in self.outed]
        if count is None:
            count = self.rng.randint(1, 3)
        count = min(count, len(alive) - self.min_in)
        if count <= 0:
            return []
        victims = self.rng.sample(alive, count)
        for osd in victims:
            if self._mon_cmd({"prefix": "osd out", "id": osd},
                             "storm out osd.%d" % osd):
                self.outed.add(osd)
        self.log.append(("out_storm", tuple(victims)))
        self._journal("out storm", "osds %s" % victims, osds=victims)
        # dwell so the out-wave's peering actually starts before the
        # in-wave reverses it — back-to-back epochs, not a no-op merge
        self._stop.wait(self.interval)
        self.in_all()
        return victims

    def in_all(self) -> None:
        """Reverse every storm-out (the in-wave)."""
        while self.outed:
            osd = self.outed.pop()
            self._mon_cmd({"prefix": "osd in", "id": osd},
                          "storm in osd.%d" % osd)
        self.log.append(("in_storm",))

    def reweight_sweep(self, count: int = 3) -> list[int]:
        """Override-reweight a few OSDs to random fractions in
        [0.5, 1.0): each accepted reweight is one committed epoch that
        MOVES RAW PLACEMENTS (weight feeds the CRUSH weight vector),
        the heavier churn class than up/down flaps."""
        alive = self._alive()
        if not alive:
            return []
        victims = self.rng.sample(alive,
                                  min(count, len(alive)))
        for osd in victims:
            w = self.rng.uniform(0.5, 0.99)
            if self._mon_cmd({"prefix": "osd reweight", "id": osd,
                              "weight": w},
                             "reweight osd.%d" % osd):
                self.reweighted.add(osd)
        self.log.append(("reweight", tuple(victims)))
        self._journal("reweight sweep", "osds %s" % victims,
                      osds=victims)
        return victims

    def restore_weights(self) -> None:
        while self.reweighted:
            osd = self.reweighted.pop()
            self._mon_cmd({"prefix": "osd reweight", "id": osd,
                           "weight": 1.0},
                          "restore weight osd.%d" % osd)
        self.log.append(("reweight_restore",))

    def pool_resize(self, grow_by: int = 8) -> int | None:
        """Grow the dedicated churn pool's pg_num (pools only grow):
        the split instantiates fresh PGs on every OSD the new masks
        land on — the map-churn class that changes the PG POPULATION
        rather than placements."""
        if not self.churn_pool:
            return None
        mon = self.cluster.leader()
        pool = next((p for p in mon.osdmon.osdmap.pools.values()
                     if p.name == self.churn_pool), None)
        if pool is None:
            return None
        target = pool.pg_num + grow_by
        if not self._mon_cmd({"prefix": "osd pool set",
                              "pool": self.churn_pool,
                              "var": "pg_num", "val": target},
                             "resize pool %s" % self.churn_pool):
            return None
        self.log.append(("pool_resize", self.churn_pool, target))
        self._journal("pool resize",
                      "%s pg_num -> %d" % (self.churn_pool, target),
                      pool=self.churn_pool, pg_num=target)
        return target

    # -- mon thrash (MonitorThrasher kill/revive) ----------------------

    def thrash_mon(self) -> int | None:
        """Kill the paxos LEADER and boot a state-empty replacement in
        its place: the survivors re-elect among themselves, and the
        rejoining mon catches up through the paxos full-state sync.
        Needs >= 3 mons so quorum survives the kill."""
        mons = self.cluster.mons
        if len(mons) < 3:
            return None
        leader = next((m for m in mons if m.is_leader()), None)
        if leader is None:
            return None
        rank, idx = leader.rank, mons.index(leader)
        self.log.append(("mon_kill", rank))
        self._journal("mon kill", "mon.%d (leader)" % rank, mon=rank)
        leader.shutdown()
        # let the survivors elect before the empty-stated rank is back
        # on the wire (mirrors a real restart's crash->reboot gap)
        wait_until(lambda: any(m.is_leader() for m in mons
                               if m is not leader), timeout=30)
        from ceph_tpu.common import Context
        from ceph_tpu.mon import Monitor
        kwargs = {}
        if getattr(self.cluster, "auth", False):
            from ceph_tpu.auth.keyring import KeyRing
            kwargs = {"keyring":
                      KeyRing.parse(self.cluster.keyring.emit()),
                      "service_secrets": self.cluster.service_secrets}
        mon = Monitor(rank, self.cluster.monmap,
                      Context(self.cluster.conf_overrides,
                              name="mon.%d" % rank), **kwargs)
        mon.init()
        if self.cluster.mgr is not None:
            mon.mgr_addr = self.cluster.mgr.addr
        mons[idx] = mon
        self.log.append(("mon_revive", rank))
        self._journal("mon revive", "mon.%d" % rank, mon=rank)
        return rank

    # -- loop ----------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                # rare chaos riders first (off by default): a mon
                # leader bounce, or a partition toggle
                if self.mon_thrash_prob and \
                        self.rng.random() < self.mon_thrash_prob:
                    self.thrash_mon()
                if self.partition_prob and \
                        self.rng.random() < self.partition_prob:
                    if self.partitions:
                        self.heal()
                    else:
                        alive = self._alive()
                        if len(alive) >= 2:
                            a, b = self.rng.sample(alive, 2)
                            self.partition(a, b)
                if self.device_thrash_prob and \
                        self.rng.random() < self.device_thrash_prob:
                    if self.dead_devices and self.rng.random() < 0.6:
                        self.revive_device()
                    else:
                        self.kill_device()
                if self.map_churn_prob and \
                        self.rng.random() < self.map_churn_prob:
                    roll = self.rng.random()
                    if roll < 0.45:
                        self.out_in_storm()
                    elif roll < 0.85 or not self.churn_pool:
                        self.reweight_sweep()
                    else:
                        self.pool_resize()
                # weighted choice mirroring the reference's thrasher:
                # mostly kill/revive churn
                if self.dead and (len(self._alive()) <= self.min_in
                                  or self.rng.random() < 0.5):
                    self.revive_one()
                    time.sleep(self.revive_delay)
                else:
                    self.kill_one()
                self._stop.wait(self.interval)
        except Exception as e:  # surface loop crashes to the test
            self.errors.append(repr(e))

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="thrasher", daemon=True)
        self._thread.start()

    def stop_and_heal(self, timeout: float = 30.0) -> None:
        """Stop thrashing, revive everything, wait for all-up."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.heal()
        self.in_all()
        self.restore_weights()
        while self.dead_devices:
            self.revive_device()
        while self.dead:
            self.revive_one()
        assert wait_until(self.cluster.all_osds_up, timeout=timeout), \
            "cluster never healed after thrash: %s" % (self.log[-6:],)
        assert not self.errors, self.errors
