"""Randomized failure injection against a live MiniCluster.

Models the reference's teuthology Thrasher
(qa/tasks/ceph_manager.py:98 — kill_osd :205, revive_osd :426): a
background loop that keeps killing and reviving OSDs (never dipping
below min_in) while a foreground workload runs, so recovery,
re-peering, and degraded IO get exercised under churn instead of in
staged one-shot tests.
"""

from __future__ import annotations

import random
import threading
import time

from .cluster_util import wait_until

__all__ = ["Thrasher"]


class Thrasher:
    def __init__(self, cluster, seed: int = 0, min_in: int = 2,
                 interval: float = 0.5, revive_delay: float = 0.8):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.min_in = min_in
        self.interval = interval
        self.revive_delay = revive_delay
        self.dead: dict[int, object] = {}     # osd_id -> store
        self.log: list[tuple[str, int]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.errors: list[str] = []

    # -- actions (kill_osd / revive_osd) -------------------------------

    def _alive(self) -> list[int]:
        return sorted(set(self.cluster.osds) - set(self.dead))

    def _journal(self, action: str, osd_id: int) -> None:
        """Record the injected fault in the mon's cluster event
        journal, so `ceph events last` interleaves what the thrasher
        DID with how the cluster REACTED (down/out epochs, health
        transitions). Best-effort: journaling must never change the
        thrash behavior itself."""
        try:
            leader = self.cluster.leader()
            eventmon = getattr(leader, "eventmon", None)
            if eventmon is not None:
                eventmon.submit(
                    "thrash", "thrasher: %s osd.%d" % (action, osd_id),
                    source="thrasher",
                    data={"action": action, "osd": osd_id})
        except Exception:
            pass

    def kill_one(self) -> int | None:
        alive = self._alive()
        if len(alive) <= self.min_in:
            return None
        victim = self.rng.choice(alive)
        store = self.cluster.stop_osd(victim)
        self.dead[victim] = store
        self.log.append(("kill", victim))
        self._journal("kill", victim)
        return victim

    def revive_one(self) -> int | None:
        if not self.dead:
            return None
        osd_id = self.rng.choice(sorted(self.dead))
        store = self.dead.pop(osd_id)
        self.cluster.revive_osd(osd_id, store=store)
        # an auto-marked-out osd needs an explicit "in" (ceph_manager
        # revive_osd does the same)
        client = self.cluster.clients[0] if self.cluster.clients else None
        if client is not None:
            try:
                client.mon_command({"prefix": "osd in", "id": osd_id})
            except Exception:
                pass
        self.log.append(("revive", osd_id))
        self._journal("revive", osd_id)
        return osd_id

    # -- loop ----------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                # weighted choice mirroring the reference's thrasher:
                # mostly kill/revive churn
                if self.dead and (len(self._alive()) <= self.min_in
                                  or self.rng.random() < 0.5):
                    self.revive_one()
                    time.sleep(self.revive_delay)
                else:
                    self.kill_one()
                self._stop.wait(self.interval)
        except Exception as e:  # surface loop crashes to the test
            self.errors.append(repr(e))

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="thrasher", daemon=True)
        self._thread.start()

    def stop_and_heal(self, timeout: float = 30.0) -> None:
        """Stop thrashing, revive everything, wait for all-up."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        while self.dead:
            self.revive_one()
        assert wait_until(self.cluster.all_osds_up, timeout=timeout), \
            "cluster never healed after thrash: %s" % (self.log[-6:],)
        assert not self.errors, self.errors
