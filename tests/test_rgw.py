"""S3-subset gateway over a live cluster (rgw_rest_s3 role)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client

import pytest

from ceph_tpu.services.rgw import RGWServer, string_to_sign

from .cluster_util import MiniCluster

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}

ACCESS, SECRET = "testkey", "testsecret"


@pytest.fixture(scope="module")
def gw():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    client = cluster.client()
    cluster.create_replicated_pool(client, "rgw", size=3, pg_num=4)
    server = RGWServer(client.open_ioctx("rgw"),
                       credentials={ACCESS: SECRET}).start()
    yield server
    server.stop()
    cluster.stop()


def request(gw_server, method, path, body=b"", sign=True,
            headers=None):
    headers = dict(headers or {})
    if sign:
        hdrs = {k.lower(): v for k, v in headers.items()}
        sts = string_to_sign(method, path.split("?")[0], hdrs)
        sig = base64.b64encode(hmac.new(
            SECRET.encode(), sts.encode(),
            hashlib.sha1).digest()).decode()
        headers["Authorization"] = "AWS %s:%s" % (ACCESS, sig)
    conn = http.client.HTTPConnection(*gw_server.addr)
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


class TestBuckets:
    def test_create_list_delete(self, gw):
        status, _, _ = request(gw, "PUT", "/mybucket")
        assert status == 200
        status, _, body = request(gw, "GET", "/")
        assert status == 200 and b"<Name>mybucket</Name>" in body
        status, _, body = request(gw, "PUT", "/mybucket")
        assert status == 409 and b"BucketAlreadyExists" in body
        status, _, _ = request(gw, "DELETE", "/mybucket")
        assert status == 204
        status, _, body = request(gw, "GET", "/")
        assert b"mybucket" not in body

    def test_delete_nonempty_refused(self, gw):
        request(gw, "PUT", "/full")
        request(gw, "PUT", "/full/obj", body=b"x")
        status, _, body = request(gw, "DELETE", "/full")
        assert status == 409 and b"BucketNotEmpty" in body
        request(gw, "DELETE", "/full/obj")
        status, _, _ = request(gw, "DELETE", "/full")
        assert status == 204


class TestObjects:
    def test_put_get_head_delete(self, gw):
        request(gw, "PUT", "/objs")
        payload = b"the quick brown payload" * 100
        status, headers, _ = request(gw, "PUT", "/objs/data.bin",
                                     body=payload)
        assert status == 200
        want_etag = '"%s"' % hashlib.md5(payload).hexdigest()
        assert headers["ETag"] == want_etag

        status, headers, body = request(gw, "GET", "/objs/data.bin")
        assert status == 200 and body == payload
        assert headers["ETag"] == want_etag

        status, headers, _ = request(gw, "HEAD", "/objs/data.bin")
        assert status == 200

        status, _, _ = request(gw, "DELETE", "/objs/data.bin")
        assert status == 204
        status, _, body = request(gw, "GET", "/objs/data.bin")
        assert status == 404 and b"NoSuchKey" in body

    def test_listing_with_prefix(self, gw):
        request(gw, "PUT", "/listb")
        for key in ("a/1", "a/2", "b/1"):
            request(gw, "PUT", "/listb/" + key, body=b"v")
        status, _, body = request(gw, "GET", "/listb?prefix=a/")
        assert status == 200
        assert b"a/1" in body and b"a/2" in body and b"b/1" not in body
        status, _, body = request(gw, "GET", "/listb?max-keys=2")
        assert body.count(b"<Contents>") == 2

    def test_missing_bucket_404(self, gw):
        status, _, body = request(gw, "GET", "/ghost/key")
        assert status == 404 and b"NoSuchBucket" in body


class TestAuth:
    def test_anonymous_denied(self, gw):
        status, _, body = request(gw, "GET", "/", sign=False)
        assert status == 403 and b"AccessDenied" in body

    def test_bad_signature_denied(self, gw):
        status, _, body = request(
            gw, "GET", "/", sign=False,
            headers={"Authorization": "AWS %s:bogus" % ACCESS})
        assert status == 403 and b"SignatureDoesNotMatch" in body

    def test_unknown_key_denied(self, gw):
        status, _, body = request(
            gw, "GET", "/", sign=False,
            headers={"Authorization": "AWS nobody:sig"})
        assert status == 403 and b"InvalidAccessKeyId" in body

    def test_data_survives_in_rados(self, gw):
        """The gateway is a view over rados: the bytes really live in
        the backing pool's objects."""
        request(gw, "PUT", "/durab")
        request(gw, "PUT", "/durab/obj", body=b"rados-backed")
        assert gw.store.ioctx.read("durab/obj") == b"rados-backed"
