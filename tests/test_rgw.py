"""S3-subset gateway over a live cluster (rgw_rest_s3 role)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client

import pytest

from ceph_tpu.services.rgw import RGWServer, string_to_sign

from .cluster_util import MiniCluster

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}

ACCESS, SECRET = "testkey", "testsecret"


@pytest.fixture(scope="module")
def gw():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    client = cluster.client()
    cluster.create_replicated_pool(client, "rgw", size=3, pg_num=4)
    server = RGWServer(client.open_ioctx("rgw"),
                       credentials={ACCESS: SECRET}).start()
    yield server
    server.stop()
    cluster.stop()


def request(gw_server, method, path, body=b"", sign=True,
            headers=None):
    headers = dict(headers or {})
    if sign:
        hdrs = {k.lower(): v for k, v in headers.items()}
        sts = string_to_sign(method, path.split("?")[0], hdrs)
        sig = base64.b64encode(hmac.new(
            SECRET.encode(), sts.encode(),
            hashlib.sha1).digest()).decode()
        headers["Authorization"] = "AWS %s:%s" % (ACCESS, sig)
    conn = http.client.HTTPConnection(*gw_server.addr)
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


class TestBuckets:
    def test_create_list_delete(self, gw):
        status, _, _ = request(gw, "PUT", "/mybucket")
        assert status == 200
        status, _, body = request(gw, "GET", "/")
        assert status == 200 and b"<Name>mybucket</Name>" in body
        status, _, body = request(gw, "PUT", "/mybucket")
        assert status == 409 and b"BucketAlreadyExists" in body
        status, _, _ = request(gw, "DELETE", "/mybucket")
        assert status == 204
        status, _, body = request(gw, "GET", "/")
        assert b"mybucket" not in body

    def test_delete_nonempty_refused(self, gw):
        request(gw, "PUT", "/full")
        request(gw, "PUT", "/full/obj", body=b"x")
        status, _, body = request(gw, "DELETE", "/full")
        assert status == 409 and b"BucketNotEmpty" in body
        request(gw, "DELETE", "/full/obj")
        status, _, _ = request(gw, "DELETE", "/full")
        assert status == 204


class TestObjects:
    def test_put_get_head_delete(self, gw):
        request(gw, "PUT", "/objs")
        payload = b"the quick brown payload" * 100
        status, headers, _ = request(gw, "PUT", "/objs/data.bin",
                                     body=payload)
        assert status == 200
        want_etag = '"%s"' % hashlib.md5(payload).hexdigest()
        assert headers["ETag"] == want_etag

        status, headers, body = request(gw, "GET", "/objs/data.bin")
        assert status == 200 and body == payload
        assert headers["ETag"] == want_etag

        status, headers, _ = request(gw, "HEAD", "/objs/data.bin")
        assert status == 200

        status, _, _ = request(gw, "DELETE", "/objs/data.bin")
        assert status == 204
        status, _, body = request(gw, "GET", "/objs/data.bin")
        assert status == 404 and b"NoSuchKey" in body

    def test_listing_with_prefix(self, gw):
        request(gw, "PUT", "/listb")
        for key in ("a/1", "a/2", "b/1"):
            request(gw, "PUT", "/listb/" + key, body=b"v")
        status, _, body = request(gw, "GET", "/listb?prefix=a/")
        assert status == 200
        assert b"a/1" in body and b"a/2" in body and b"b/1" not in body
        status, _, body = request(gw, "GET", "/listb?max-keys=2")
        assert body.count(b"<Contents>") == 2

    def test_missing_bucket_404(self, gw):
        status, _, body = request(gw, "GET", "/ghost/key")
        assert status == 404 and b"NoSuchBucket" in body


class TestAuth:
    def test_anonymous_denied(self, gw):
        status, _, body = request(gw, "GET", "/", sign=False)
        assert status == 403 and b"AccessDenied" in body

    def test_bad_signature_denied(self, gw):
        status, _, body = request(
            gw, "GET", "/", sign=False,
            headers={"Authorization": "AWS %s:bogus" % ACCESS})
        assert status == 403 and b"SignatureDoesNotMatch" in body

    def test_unknown_key_denied(self, gw):
        status, _, body = request(
            gw, "GET", "/", sign=False,
            headers={"Authorization": "AWS nobody:sig"})
        assert status == 403 and b"InvalidAccessKeyId" in body

    def test_data_survives_in_rados(self, gw):
        """The gateway is a view over rados: the bytes really live in
        the backing pool's objects."""
        request(gw, "PUT", "/durab")
        request(gw, "PUT", "/durab/obj", body=b"rados-backed")
        assert gw.store.ioctx.read("durab/obj") == b"rados-backed"


class TestMultipartUpload:
    def test_full_multipart_flow(self, gw):
        import re as _re
        request(gw, "PUT", "/mp")
        # initiate
        status, _, body = request(gw, "POST", "/mp/big?uploads")
        assert status == 200
        upload_id = _re.search(
            rb"<UploadId>([0-9a-f]+)</UploadId>", body).group(1).decode()
        # the in-progress upload is listed
        status, _, body = request(gw, "GET", "/mp?uploads")
        assert status == 200 and upload_id.encode() in body
        # upload three parts (out of order on the wire is fine)
        parts_data = [b"A" * 5000, b"B" * 7000, b"C" * 100]
        etags = {}
        for n in (2, 1, 3):
            status, hdrs, _ = request(
                gw, "PUT", "/mp/big?partNumber=%d&uploadId=%s"
                % (n, upload_id), body=parts_data[n - 1])
            assert status == 200
            etags[n] = hdrs["ETag"].strip('"')
        # complete with ascending part order
        xml = ("<CompleteMultipartUpload>" + "".join(
            "<Part><PartNumber>%d</PartNumber><ETag>\"%s\"</ETag></Part>"
            % (n, etags[n]) for n in (1, 2, 3)) +
            "</CompleteMultipartUpload>").encode()
        status, _, body = request(
            gw, "POST", "/mp/big?uploadId=%s" % upload_id, body=xml)
        assert status == 200 and b"-3" in body   # multipart etag '-N'
        # the assembled object reads back whole
        status, _, body = request(gw, "GET", "/mp/big")
        assert status == 200
        assert body == b"".join(parts_data)
        # state + part objects are gone
        status, _, body = request(gw, "GET", "/mp?uploads")
        assert upload_id.encode() not in body

    def test_complete_with_wrong_etag_rejected(self, gw):
        import re as _re
        request(gw, "PUT", "/mp2")
        _, _, body = request(gw, "POST", "/mp2/x?uploads")
        upload_id = _re.search(
            rb"<UploadId>([0-9a-f]+)</UploadId>", body).group(1).decode()
        request(gw, "PUT", "/mp2/x?partNumber=1&uploadId=%s" % upload_id,
                body=b"data")
        xml = (b"<CompleteMultipartUpload><Part><PartNumber>1"
               b"</PartNumber><ETag>\"deadbeef\"</ETag></Part>"
               b"</CompleteMultipartUpload>")
        status, _, body = request(
            gw, "POST", "/mp2/x?uploadId=%s" % upload_id, body=xml)
        assert status == 400 and b"InvalidPart" in body

    def test_abort_cleans_up(self, gw):
        import re as _re
        request(gw, "PUT", "/mp3")
        _, _, body = request(gw, "POST", "/mp3/y?uploads")
        upload_id = _re.search(
            rb"<UploadId>([0-9a-f]+)</UploadId>", body).group(1).decode()
        request(gw, "PUT", "/mp3/y?partNumber=1&uploadId=%s" % upload_id,
                body=b"zzz")
        status, _, _ = request(
            gw, "DELETE", "/mp3/y?uploadId=%s" % upload_id)
        assert status == 204
        status, _, body = request(gw, "GET", "/mp3?uploads")
        assert upload_id.encode() not in body
        # completing an aborted upload is NoSuchUpload
        status, _, body = request(
            gw, "POST", "/mp3/y?uploadId=%s" % upload_id,
            body=b"<CompleteMultipartUpload><Part><PartNumber>1"
                 b"</PartNumber><ETag>\"00\"</ETag></Part>"
                 b"</CompleteMultipartUpload>")
        assert status == 404 and b"NoSuchUpload" in body


class TestRangeGet:
    def test_byte_ranges(self, gw):
        request(gw, "PUT", "/rg")
        payload = bytes(range(256)) * 4
        request(gw, "PUT", "/rg/obj", body=payload)
        status, hdrs, body = request(gw, "GET", "/rg/obj",
                                     headers={"Range": "bytes=10-19"})
        assert status == 206
        assert body == payload[10:20]
        assert hdrs["Content-Range"] == "bytes 10-19/1024"
        # open-ended and suffix forms
        status, _, body = request(gw, "GET", "/rg/obj",
                                  headers={"Range": "bytes=1000-"})
        assert status == 206 and body == payload[1000:]
        status, _, body = request(gw, "GET", "/rg/obj",
                                  headers={"Range": "bytes=-24"})
        assert status == 206 and body == payload[-24:]
        # unsatisfiable
        status, _, _ = request(gw, "GET", "/rg/obj",
                               headers={"Range": "bytes=5000-"})
        assert status == 416


def swift_request(gw_server, method, path, body=b"", headers=None):
    conn = http.client.HTTPConnection(*gw_server.addr)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


@pytest.fixture(scope="module")
def swift_token(gw):
    status, hdrs, _ = swift_request(
        gw, "GET", "/auth/v1.0",
        headers={"X-Auth-User": ACCESS, "X-Auth-Key": SECRET})
    assert status == 200
    return hdrs["X-Auth-Token"]


class TestSwiftFront:
    def test_auth_handshake(self, gw):
        status, hdrs, _ = swift_request(
            gw, "GET", "/auth/v1.0",
            headers={"X-Auth-User": ACCESS, "X-Auth-Key": SECRET})
        assert status == 200
        assert hdrs["X-Auth-Token"].startswith("AUTH_tk")
        assert "/swift/v1" in hdrs["X-Storage-Url"]

    def test_bad_credentials_401(self, gw):
        status, _, _ = swift_request(
            gw, "GET", "/auth/v1.0",
            headers={"X-Auth-User": ACCESS, "X-Auth-Key": "wrong"})
        assert status == 401

    def test_container_and_object_flow(self, gw, swift_token):
        tok = {"X-Auth-Token": swift_token}
        status, _, _ = swift_request(gw, "PUT", "/swift/v1/swc",
                                     headers=tok)
        assert status == 201
        # re-PUT of an existing container is 202, not an error
        status, _, _ = swift_request(gw, "PUT", "/swift/v1/swc",
                                     headers=tok)
        assert status == 202
        status, hdrs, _ = swift_request(
            gw, "PUT", "/swift/v1/swc/hello.txt", body=b"swift bytes",
            headers=tok)
        assert status == 201 and hdrs["Etag"]
        status, _, body = swift_request(
            gw, "GET", "/swift/v1/swc/hello.txt", headers=tok)
        assert status == 200 and body == b"swift bytes"
        status, _, body = swift_request(gw, "GET", "/swift/v1/swc",
                                        headers=tok)
        assert status == 200 and b"hello.txt" in body
        status, hdrs, _ = swift_request(gw, "HEAD", "/swift/v1/swc",
                                        headers=tok)
        assert status == 204
        assert hdrs["X-Container-Object-Count"] == "1"
        status, _, _ = swift_request(
            gw, "DELETE", "/swift/v1/swc/hello.txt", headers=tok)
        assert status == 204
        status, _, _ = swift_request(gw, "DELETE", "/swift/v1/swc",
                                     headers=tok)
        assert status == 204

    def test_account_listing(self, gw, swift_token):
        tok = {"X-Auth-Token": swift_token}
        swift_request(gw, "PUT", "/swift/v1/swacct", headers=tok)
        status, _, body = swift_request(gw, "GET", "/swift/v1",
                                        headers=tok)
        assert status == 200 and b"swacct" in body
        swift_request(gw, "DELETE", "/swift/v1/swacct", headers=tok)

    def test_unauthenticated_swift_denied(self, gw):
        status, _, _ = swift_request(gw, "PUT", "/swift/v1/anon")
        assert status == 403
        status, _, _ = swift_request(gw, "GET", "/swift/v1")
        assert status == 403


class TestCrossFrontACLs:
    """Canned ACLs gate anonymous access identically on both fronts:
    containers and buckets share one roster, one ACL store."""

    def test_s3_acl_opens_swift_anonymous_read(self, gw, swift_token):
        request(gw, "PUT", "/xfront",
                headers={"x-amz-acl": "public-read"})
        request(gw, "PUT", "/xfront/pub.txt", body=b"open data")
        # anonymous Swift GET sees the S3-created public bucket
        status, _, body = swift_request(
            gw, "GET", "/swift/v1/xfront/pub.txt")
        assert status == 200 and body == b"open data"
        # but anonymous write is still denied (public-read only)
        status, _, _ = swift_request(
            gw, "PUT", "/swift/v1/xfront/evil", body=b"x")
        assert status == 403
        request(gw, "DELETE", "/xfront/pub.txt")
        request(gw, "DELETE", "/xfront")

    def test_swift_acl_opens_s3_anonymous_read(self, gw, swift_token):
        tok = {"X-Auth-Token": swift_token}
        swift_request(gw, "PUT", "/swift/v1/xf2",
                      headers=dict(tok, **{"X-Container-Read": ".r:*"}))
        swift_request(gw, "PUT", "/swift/v1/xf2/o", body=b"shared",
                      headers=tok)
        status, _, body = request(gw, "GET", "/xf2/o", sign=False)
        assert status == 200 and body == b"shared"
        # anonymous S3 PUT denied on a read-only container
        status, _, _ = request(gw, "PUT", "/xf2/w", body=b"x",
                               sign=False)
        assert status == 403
        swift_request(gw, "DELETE", "/swift/v1/xf2/o", headers=tok)
        swift_request(gw, "DELETE", "/swift/v1/xf2", headers=tok)

    def test_public_read_write_allows_anonymous_put(self, gw,
                                                    swift_token):
        tok = {"X-Auth-Token": swift_token}
        swift_request(
            gw, "PUT", "/swift/v1/xf3",
            headers=dict(tok, **{"X-Container-Write": ".r:*",
                                 "X-Container-Read": ".r:*"}))
        status, _, _ = request(gw, "PUT", "/xf3/anon-obj", body=b"w",
                               sign=False)
        assert status == 200
        status, _, body = swift_request(gw, "GET",
                                        "/swift/v1/xf3/anon-obj")
        assert status == 200 and body == b"w"
        swift_request(gw, "DELETE", "/swift/v1/xf3/anon-obj",
                      headers=tok)
        swift_request(gw, "DELETE", "/swift/v1/xf3", headers=tok)

    def test_acl_update_via_post_and_subresource(self, gw, swift_token):
        tok = {"X-Auth-Token": swift_token}
        request(gw, "PUT", "/xf4")       # default private
        status, _, _ = request(gw, "GET", "/xf4/nope", sign=False)
        assert status == 403
        # Swift POST flips it to public-read
        status, _, _ = swift_request(
            gw, "POST", "/swift/v1/xf4",
            headers=dict(tok, **{"X-Container-Read": ".r:*"}))
        assert status == 204
        status, _, body = request(gw, "GET", "/xf4?acl")
        assert status == 200 and b"public-read" in body
        # S3 ?acl subresource flips it back
        status, _, _ = request(gw, "PUT", "/xf4?acl",
                               headers={"x-amz-acl": "private"})
        assert status == 200
        status, _, _ = request(gw, "GET", "/xf4?acl", sign=False)
        assert status == 403             # acl read is owner-only
        status, hdrs, _ = swift_request(gw, "HEAD", "/swift/v1/xf4",
                                        headers=tok)
        assert "X-Container-Read" not in hdrs
        request(gw, "DELETE", "/xf4")

    def test_bogus_canned_acl_rejected(self, gw):
        status, _, body = request(
            gw, "PUT", "/xf5", headers={"x-amz-acl": "authenticated-read"})
        assert status == 400 and b"InvalidArgument" in body


class TestMultipartEdgeCases:
    def test_etag_before_partnumber_order_accepted(self, gw):
        """AWS's own CompleteMultipartUpload request syntax puts ETag
        BEFORE PartNumber inside <Part>; both orders must parse."""
        import re as _re
        request(gw, "PUT", "/mp4")
        _, _, body = request(gw, "POST", "/mp4/k?uploads")
        upload_id = _re.search(
            rb"<UploadId>([0-9a-f]+)</UploadId>", body).group(1).decode()
        _, hdrs, _ = request(
            gw, "PUT", "/mp4/k?partNumber=1&uploadId=%s" % upload_id,
            body=b"hello-multipart")
        etag = hdrs["ETag"].strip('"')
        xml = ("<CompleteMultipartUpload><Part>"
               "<ETag>\"%s\"</ETag><PartNumber>1</PartNumber>"
               "</Part></CompleteMultipartUpload>" % etag).encode()
        status, _, _ = request(
            gw, "POST", "/mp4/k?uploadId=%s" % upload_id, body=xml)
        assert status == 200
        status, _, body = request(gw, "GET", "/mp4/k")
        assert status == 200 and body == b"hello-multipart"

    def test_delete_bucket_with_inflight_upload_refused(self, gw):
        request(gw, "PUT", "/mp5")
        status, _, _ = request(gw, "POST", "/mp5/z?uploads")
        assert status == 200
        status, _, body = request(gw, "DELETE", "/mp5")
        assert status == 409 and b"BucketNotEmpty" in body

    def test_bad_part_number_is_400(self, gw):
        request(gw, "PUT", "/mp6")
        import re as _re
        _, _, body = request(gw, "POST", "/mp6/q?uploads")
        upload_id = _re.search(
            rb"<UploadId>([0-9a-f]+)</UploadId>", body).group(1).decode()
        status, _, body = request(
            gw, "PUT", "/mp6/q?partNumber=abc&uploadId=%s" % upload_id,
            body=b"x")
        assert status == 400 and b"InvalidArgument" in body
