"""crushtool / osdmaptool CLI tests.

Models the reference's offline-tooling checks: compile/decompile
round-trips (crushtool is the validation oracle for CRUSH edits,
src/tools/crushtool.cc), CrushTester distribution runs, osdmaptool
--createsimple / --test-map-pgs / --upmap
(src/tools/osdmaptool.cc).
"""

import json

import numpy as np
import pytest

from ceph_tpu.osd.osd_map import OSDMapMapping, PGID
from ceph_tpu.tools import crushtool, osdmaptool

SAMPLE_MAP = """
# begin crush map
tunable choose_local_tries 0
tunable choose_total_tries 50
tunable chooseleaf_vary_r 1

# devices
device 0 osd.0
device 1 osd.1 class ssd
device 2 osd.2
device 3 osd.3

# types
type 0 osd
type 1 host
type 2 root

# buckets
host host0 {
\tid -2
\talg straw2
\thash 0
\titem osd.0 weight 1.000
\titem osd.1 weight 2.000
}
host host1 {
\tid -3
\talg straw2
\thash 0
\titem osd.2 weight 1.000
\titem osd.3 weight 1.000
}
root default {
\tid -1
\talg straw2
\thash 0
\titem host0 weight 3.000
\titem host1 weight 2.000
}

# rules
rule replicated_rule {
\truleset 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
rule ec_rule {
\truleset 1
\ttype erasure
\tmin_size 3
\tmax_size 4
\tstep set_chooseleaf_tries 5
\tstep take default
\tstep chooseleaf indep 0 type osd
\tstep emit
}
# end crush map
"""


class TestCrushCompile:
    def test_compile_basics(self):
        m = crushtool.compile_text(SAMPLE_MAP)
        assert set(m.bucket_names) == {"host0", "host1", "default"}
        assert m.max_devices == 4
        assert m.device_classes == {1: "ssd"}
        assert m.tunables.choose_total_tries == 50
        assert len(m.rules) == 2
        assert m.rules[1].type == crushtool.POOL_TYPE_ERASURE
        assert m.rules[1].steps[0] == (
            crushtool.RULE_SET_CHOOSELEAF_TRIES, 5)
        b = m.buckets[m.bucket_names["host0"]]
        assert list(b.items) == [0, 1]
        assert list(b.weights) == [0x10000, 0x20000]

    def test_decompile_compile_roundtrip(self):
        m1 = crushtool.compile_text(SAMPLE_MAP)
        text = crushtool.decompile(m1)
        m2 = crushtool.compile_text(text)
        # identical mapping behavior, not just identical structure
        for ruleno in range(2):
            for x in range(64):
                assert crushtool.crush_do_rule(m1, ruleno, x, 3) == \
                    crushtool.crush_do_rule(m2, ruleno, x, 3)

    def test_json_roundtrip(self):
        m1 = crushtool.compile_text(SAMPLE_MAP)
        m2 = crushtool.map_from_json(
            json.loads(json.dumps(crushtool.map_to_json(m1))))
        for x in range(64):
            assert crushtool.crush_do_rule(m1, 0, x, 3) == \
                crushtool.crush_do_rule(m2, 0, x, 3)

    @pytest.mark.parametrize("bad,msg", [
        ("tunable bogus 1", "tunable"),
        ("device 0 osd.1", "named"),
        ("rule r {\nstep fly\n}", "step"),
        ("type 1 host\nhost h {\nitem osd.0\nalg nope\n}", "alg"),
        ("type 1 host\nhost h {\nid -1\nalg straw2\n", "unterminated"),
    ])
    def test_compile_errors(self, bad, msg):
        with pytest.raises(crushtool.CompileError, match=msg):
            crushtool.compile_text(bad)

    def test_build(self):
        m = crushtool.build_map(8, [("host", "straw2", 2),
                                    ("root", "straw2", 0)])
        assert len([b for b in m.buckets.values() if b.type == 1]) == 4
        assert "default" in m.bucket_names
        m.add_simple_rule("r", "default", failure_domain="host")
        res = crushtool.crush_do_rule(m, 0, 1234, 3)
        assert len(set(res)) == 3
        # failure-domain separation: chosen osds live on distinct hosts
        hosts = {dev // 2 for dev in res}
        assert len(hosts) == 3


class TestCrushTester:
    def test_distribution_and_report(self):
        m = crushtool.compile_text(SAMPLE_MAP)
        counts, results = crushtool.run_test(m, 0, 2, 0, 255)
        assert counts.sum() == 2 * 256
        assert all(c > 0 for c in counts)  # every device used
        report = crushtool.format_test_report(m, counts, results, 0, 2)
        assert "num_rep 2" in report and "stddev" in report

    def test_batched_matches_reference(self):
        m = crushtool.compile_text(SAMPLE_MAP)
        c_ref, r_ref = crushtool.run_test(m, 1, 4, 0, 127)
        c_bat, r_bat = crushtool.run_test(m, 1, 4, 0, 127, batched=True)
        assert r_ref == r_bat
        assert list(c_ref) == list(c_bat)

    def test_cli(self, tmp_path, capsys):
        src = tmp_path / "map.txt"
        src.write_text(SAMPLE_MAP)
        cmp_file = tmp_path / "map.json"
        assert crushtool.main(["-c", str(src), "-o", str(cmp_file)]) == 0
        assert crushtool.main(["-d", str(cmp_file)]) == 0
        out = capsys.readouterr().out
        assert "step take default" in out
        assert crushtool.main(
            ["-i", str(cmp_file), "--test", "--rule", "0",
             "--num-rep", "3", "--max-x", "63", "--show-utilization"]) == 0
        assert "stddev" in capsys.readouterr().out
        assert crushtool.main(["-d", str(tmp_path / "nope.json")]) == 1


class TestOsdMapTool:
    def test_createsimple_and_map(self, tmp_path):
        m = osdmaptool.create_simple(8, pg_num=64, pool_size=3, hosts=4)
        assert m.max_osd == 8
        assert all(m.is_up(o) and m.is_in(o) for o in range(8))
        up, up_p, acting, acting_p = m.pg_to_up_acting_osds(PGID(0, 5))
        assert len(acting) == 3 and acting_p in acting
        hosts = {o // 2 for o in acting}
        assert len(hosts) == 3  # host failure domain honored

    def test_json_roundtrip_preserves_mapping(self):
        m1 = osdmaptool.create_simple(6, pg_num=32)
        doc = json.loads(json.dumps(osdmaptool.osdmap_to_json(m1)))
        m2 = osdmaptool.osdmap_from_json(doc)
        for ps in range(32):
            assert m1.pg_to_up_acting_osds(PGID(0, ps)) == \
                m2.pg_to_up_acting_osds(PGID(0, ps))

    def test_test_map_pgs_report(self):
        m = osdmaptool.create_simple(8, pg_num=64)
        report = osdmaptool.test_map_pgs(m)
        assert "#osd\tcount" in report
        assert "total 64 pgs" in report
        assert "osd.7" in report
        # min/max lines must agree with the per-osd table
        counts = [int(line.split("\t")[1]) for line in report.splitlines()
                  if line.startswith("osd.")]
        min_line = next(line for line in report.splitlines()
                        if line.startswith(" min "))
        assert int(min_line.split()[-1]) == min(counts)

    def test_batched_matches_sequential(self):
        m = osdmaptool.create_simple(8, pg_num=64, hosts=4)
        a = OSDMapMapping(); a.update(m, batched=False)
        b = OSDMapMapping(); b.update(m, batched=True)
        assert a.by_pg == b.by_pg

    def test_upmap_balances(self):
        m = osdmaptool.create_simple(5, pg_num=64, pool_size=2, hosts=5)
        mapping = OSDMapMapping()
        mapping.update(m, batched=False)
        before = np.zeros(m.max_osd, dtype=np.int64)
        for _, (_, _, acting, _) in mapping.by_pg.items():
            for o in acting:
                before[o] += 1
        res = osdmaptool.calc_pg_upmaps(m, max_changes=20,
                                        use_device=False)
        assert res.num_changed  # an uneven 5-osd map has moves to make
        inc = osdmaptool.Incremental(m.epoch + 1)
        res.apply_to(inc)
        m.apply_incremental(inc)
        mapping.update(m, batched=False)
        after = np.zeros(m.max_osd, dtype=np.int64)
        for _, (_, _, acting, _) in mapping.by_pg.items():
            for o in acting:
                after[o] += 1
        assert after.max() - after.min() <= before.max() - before.min()
        assert after.sum() == before.sum()  # no replicas lost

    def test_cli_flow(self, tmp_path, capsys):
        mapfile = tmp_path / "osdmap.json"
        assert osdmaptool.main(
            ["--createsimple", "8", str(mapfile), "--pg-num", "32"]) == 0
        assert osdmaptool.main([str(mapfile), "--print"]) == 0
        assert "pools 0 'rbd'" in capsys.readouterr().out
        assert osdmaptool.main([str(mapfile), "--test-map-pgs"]) == 0
        assert "total 32 pgs" in capsys.readouterr().out
        assert osdmaptool.main(
            [str(mapfile), "--test-map-object", "foo", "--pool", "0"]) == 0
        assert "object 'foo'" in capsys.readouterr().out
        upfile = tmp_path / "upmaps.txt"
        assert osdmaptool.main([str(mapfile), "--upmap", str(upfile)]) == 0
        capsys.readouterr()
        assert osdmaptool.main(
            [str(mapfile), "--mark-down", "3", "-o", str(mapfile)]) == 0
        assert osdmaptool.main([str(mapfile), "--test-map-pgs"]) == 0
        assert "total 32 pgs" in capsys.readouterr().out


class TestChooseArgsTooling:
    def test_crushtool_text_roundtrip_choose_args(self):
        """compile -> decompile -> compile keeps choose_args exact
        (weight-set %.6f text recovers 16.16 under round)."""
        from ceph_tpu.tools import crushtool
        from .test_crush import make_two_level
        import numpy as np
        rng = np.random.default_rng(51)
        m = make_two_level(3, 2, rng.integers(
            0x10000, 3 * 0x10000, size=6, dtype=np.uint32))
        m.bucket_names.update({"host%d" % h: -2 - h for h in range(3)})
        m.choose_args[0] = {
            -1: {"ids": [11, 12, 13],
                 "weight_set": [[0x18000, 0x10000, 0x2ABCD],
                                [0x10000, 0x20000, 0x00001]]},
            -2: {"ids": None, "weight_set": [[0x8000, 0x1777]]},
        }
        text = crushtool.decompile(m)
        assert "choose_args 0 {" in text
        m2 = crushtool.compile_text(text)
        assert m2.choose_args == m.choose_args
        # JSON container carries it too
        doc = crushtool.map_to_json(m)
        m3 = crushtool.map_from_json(doc)
        assert m3.choose_args == m.choose_args

    def test_choose_args_rides_the_wire_codec(self):
        from ceph_tpu import codecs  # noqa: F401 — arms the registry
        from ceph_tpu import encoding
        from .test_crush import make_flat
        import numpy as np
        m = make_flat(4, np.full(4, 0x10000, dtype=np.uint32))
        m.choose_args[-1] = {-1: {"ids": None,
                                  "weight_set": [[1, 2, 3, 4]]}}
        blob = encoding.encode_any(m)
        m2 = encoding.decode_any(blob)
        assert m2.choose_args == m.choose_args

    def test_osdmap_pool_choose_args_index(self):
        """OSDMap mapping selects the pool's choose_args set with
        default fallback — a default weight-set remaps a pool's PGs
        without touching base weights (the balancer flow end-to-end
        through OSDMap)."""
        import numpy as np
        from ceph_tpu.crush import map as cmap_mod
        from ceph_tpu.osd.osd_map import PGID
        from .test_osd_map import build_map
        m = build_map(num_hosts=3, osds_per_host=2)
        pool_id = next(iter(m.pools))
        pool = m.pools[pool_id]
        before = {ps: m.pg_to_up_acting_osds(PGID(pool_id, ps))[0]
                  for ps in range(pool.pg_num)}
        base = {bid: b.weights.copy()
                for bid, b in m.crush.buckets.items()}
        m.crush.create_choose_args(cmap_mod.DEFAULT_CHOOSE_ARGS)
        # zero out osd.0 in the weight-set of whichever bucket holds it
        for bid, b in m.crush.buckets.items():
            if 0 in list(b.items):
                m.crush.choose_args_adjust_item_weight(
                    cmap_mod.DEFAULT_CHOOSE_ARGS, bid, 0, 0)
        after = {ps: m.pg_to_up_acting_osds(PGID(pool_id, ps))[0]
                 for ps in range(pool.pg_num)}
        for bid, b in m.crush.buckets.items():
            assert np.array_equal(b.weights, base[bid])
        assert any(0 in v for v in before.values())
        assert not any(0 in v for v in after.values())
        assert before != after
