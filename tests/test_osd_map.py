"""OSDMap: placement pipeline, overrides, incrementals, bulk mapping.

Models the mapping assertions of src/test/osd/TestOSDMap.cc (upmap,
pg_temp, primary affinity) and the OSDMapMapping parity checks."""

import numpy as np
import pytest

from ceph_tpu.crush.map import (CRUSH_ITEM_NONE, CrushMap,
                                POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED)
from ceph_tpu.osd.osd_map import (Incremental, OSDMap, OSDMapMapping, PGID,
                                  PGPool, stable_mod, str_hash_rjenkins)


def build_map(num_hosts=4, osds_per_host=2, pool_type=POOL_TYPE_REPLICATED,
              size=3, pg_num=32):
    """num_hosts hosts x osds_per_host devices, one rule over hosts."""
    m = OSDMap()
    crush = CrushMap()
    crush.type_names = {"osd": 0, "host": 1, "root": 10}
    host_ids = []
    n = num_hosts * osds_per_host
    for h in range(num_hosts):
        devs = list(range(h * osds_per_host, (h + 1) * osds_per_host))
        hid = crush.add_bucket("straw2", 1, devs, [0x10000] * len(devs),
                               name="host%d" % h)
        host_ids.append(hid)
    crush.add_bucket("straw2", 10, host_ids, [0x10000 * osds_per_host] *
                     num_hosts, name="default")
    mode = "firstn" if pool_type == POOL_TYPE_REPLICATED else "indep"
    crush.add_simple_rule("data", "default", failure_domain="host",
                          mode=mode, rule_type=pool_type)
    inc = Incremental(1)
    inc.new_max_osd = n
    inc.new_crush = crush
    inc.new_pools[1] = PGPool(pool_id=1, name="p", type=pool_type,
                              size=size, pg_num=pg_num, crush_rule=0)
    for osd in range(n):
        inc.new_up[osd] = ("127.0.0.1", 7000 + osd)
        inc.new_weight[osd] = 0x10000
    m.apply_incremental(inc)
    return m


class TestHashAndMod:
    def test_stable_mod(self):
        # growing pg_num splits buckets without moving everything
        assert stable_mod(5, 8, 15) == 5
        assert stable_mod(13, 8, 15) == 5   # 13&15=13 >= 8 -> 13&7=5
        assert stable_mod(11, 12, 15) == 11  # 11 < 12: keeps its bucket

    def test_known_rjenkins_vectors(self):
        # pinned vector (verified against the compiled reference)
        assert str_hash_rjenkins(b"") == 3175731469
        assert str_hash_rjenkins("foo") == str_hash_rjenkins(b"foo")
        assert str_hash_rjenkins("foo") != str_hash_rjenkins("bar")

    def test_rjenkins_differential(self):
        """Bit-exact vs the reference C, compiled as an oracle."""
        import ctypes
        import random
        import subprocess
        import tempfile

        src = "/root/reference/src/common/ceph_hash.cc"
        try:
            tmp = tempfile.mkdtemp(prefix="hash_oracle_")
            so = tmp + "/libh.so"
            # the file only needs __u32; provide include/types.h shim
            inc = tmp + "/include"
            import os
            os.makedirs(inc)
            with open(inc + "/types.h", "w") as f:
                f.write("typedef unsigned int __u32;\n"
                        "#define CEPH_STR_HASH_LINUX 0x1\n"
                        "#define CEPH_STR_HASH_RJENKINS 0x2\n")
            subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-I", tmp,
                            "-o", so, src], check=True,
                           capture_output=True, timeout=120)
            lib = ctypes.CDLL(so)
        except Exception:
            pytest.skip("reference hash oracle unavailable")
        fn = None
        for sym in ("ceph_str_hash_rjenkins",        # extern "C" linkage
                    "_Z22ceph_str_hash_rjenkinsPKcj"):  # C++ mangled
            try:
                fn = getattr(lib, sym)
                break
            except AttributeError:
                continue
        if fn is None:
            pytest.skip("symbol not found")
        fn.restype = ctypes.c_uint
        rng = random.Random(7)
        for _ in range(500):
            n = rng.randrange(0, 64)
            s = bytes(rng.randrange(256) for _ in range(n))
            assert fn(s, n) == str_hash_rjenkins(s)


class TestPlacementPipeline:
    def test_replicated_mapping_basics(self):
        m = build_map()
        for ps in range(32):
            up, upp, acting, actp = m.pg_to_up_acting_osds(PGID(1, ps))
            assert len(up) == 3
            assert len(set(up)) == 3
            assert upp == up[0]
            assert acting == up and actp == upp
            # failure domain: one osd per host
            hosts = {o // 2 for o in up}
            assert len(hosts) == 3

    def test_ec_holes_preserved(self):
        m = build_map(pool_type=POOL_TYPE_ERASURE, size=3)
        # kill one osd: EC mapping keeps a positional hole
        inc = Incremental(2)
        inc.new_down = [0]
        m.apply_incremental(inc)
        saw_hole = False
        for ps in range(32):
            up, upp, acting, actp = m.pg_to_up_acting_osds(PGID(1, ps))
            assert len(up) == 3
            for i, o in enumerate(up):
                if o == CRUSH_ITEM_NONE:
                    saw_hole = True
                else:
                    assert o != 0
        assert saw_hole

    def test_replicated_shifts_down_osds(self):
        m = build_map()
        inc = Incremental(2)
        inc.new_down = [0, 1]  # whole host down
        m.apply_incremental(inc)
        for ps in range(32):
            up, _, _, _ = m.pg_to_up_acting_osds(PGID(1, ps))
            assert CRUSH_ITEM_NONE not in up
            assert 0 not in up and 1 not in up

    def test_out_osd_remapped(self):
        m = build_map()
        before = {ps: m.pg_to_up_acting_osds(PGID(1, ps))[0]
                  for ps in range(32)}
        inc = Incremental(2)
        inc.new_weight[3] = 0  # mark out: CRUSH reweights around it
        m.apply_incremental(inc)
        for ps in range(32):
            up, _, _, _ = m.pg_to_up_acting_osds(PGID(1, ps))
            assert 3 not in up
            assert len(up) == 3
        assert any(3 in osds for osds in before.values())

    def test_pg_temp_overlay(self):
        m = build_map()
        pgid = PGID(1, 0)
        up, upp, _, _ = m.pg_to_up_acting_osds(pgid)
        temp = [o for o in range(8) if o not in up][:3]
        inc = Incremental(2)
        inc.new_pg_temp[pgid] = temp
        m.apply_incremental(inc)
        up2, upp2, acting, actp = m.pg_to_up_acting_osds(pgid)
        assert up2 == up          # up unchanged
        assert acting == temp     # acting overridden
        assert actp == temp[0]
        # clearing restores
        inc2 = Incremental(3)
        inc2.new_pg_temp[pgid] = []
        m.apply_incremental(inc2)
        _, _, acting3, _ = m.pg_to_up_acting_osds(pgid)
        assert acting3 == up

    def test_primary_temp(self):
        m = build_map()
        pgid = PGID(1, 5)
        up, upp, _, _ = m.pg_to_up_acting_osds(pgid)
        inc = Incremental(2)
        inc.new_primary_temp[pgid] = up[1]
        m.apply_incremental(inc)
        _, _, acting, actp = m.pg_to_up_acting_osds(pgid)
        assert actp == up[1]
        assert acting == up

    def test_pg_upmap(self):
        m = build_map()
        pgid = PGID(1, 3)
        up, _, _, _ = m.pg_to_up_acting_osds(pgid)
        target = [o for o in range(8) if o not in up][:3]
        inc = Incremental(2)
        inc.new_pg_upmap[pgid] = target
        m.apply_incremental(inc)
        up2, _, _, _ = m.pg_to_up_acting_osds(pgid)
        assert up2 == target

    def test_pg_upmap_items(self):
        m = build_map()
        pgid = PGID(1, 7)
        up, _, _, _ = m.pg_to_up_acting_osds(pgid)
        spare = [o for o in range(8) if o not in up][0]
        inc = Incremental(2)
        inc.new_pg_upmap_items[pgid] = [(up[1], spare)]
        m.apply_incremental(inc)
        up2, _, _, _ = m.pg_to_up_acting_osds(pgid)
        assert up2[1] == spare
        assert up2[0] == up[0] and up2[2] == up[2]

    def test_upmap_to_out_osd_rejected(self):
        m = build_map()
        pgid = PGID(1, 2)
        up, _, _, _ = m.pg_to_up_acting_osds(pgid)
        spare = [o for o in range(8) if o not in up][0]
        inc = Incremental(2)
        inc.new_weight[spare] = 0  # out
        inc.new_pg_upmap[pgid] = [spare] + up[1:]
        m.apply_incremental(inc)
        up2, _, _, _ = m.pg_to_up_acting_osds(pgid)
        assert spare not in up2  # explicit mapping ignored

    def test_primary_affinity_zero_never_primary(self):
        m = build_map()
        inc = Incremental(2)
        inc.new_primary_affinity[0] = 0
        inc.new_primary_affinity[1] = 0
        m.apply_incremental(inc)
        for ps in range(32):
            up, upp, _, actp = m.pg_to_up_acting_osds(PGID(1, ps))
            if set(up) - {0, 1}:
                assert upp not in (0, 1)

    def test_unknown_pool_and_ps(self):
        m = build_map(pg_num=8)
        assert m.pg_to_up_acting_osds(PGID(9, 0)) == ([], -1, [], -1)
        assert m.pg_to_up_acting_osds(PGID(1, 8)) == ([], -1, [], -1)


class TestOSDMapMapping:
    @pytest.mark.parametrize("pool_type", [POOL_TYPE_REPLICATED,
                                           POOL_TYPE_ERASURE])
    def test_bulk_equals_scalar(self, pool_type):
        m = build_map(pool_type=pool_type, pg_num=64)
        # make it interesting: one down osd, one out, a pg_temp, an upmap
        inc = Incremental(2)
        inc.new_down = [2]
        inc.new_weight[5] = 0
        inc.new_pg_temp[PGID(1, 1)] = [6, 7, 4]
        inc.new_pg_upmap_items[PGID(1, 9)] = [(0, 6)]
        m.apply_incremental(inc)

        batched = OSDMapMapping()
        batched.update(m, batched=True)
        scalar = OSDMapMapping()
        scalar.update(m, batched=False)
        assert batched.by_pg == scalar.by_pg
        assert batched.epoch == m.epoch

    def test_by_osd_index(self):
        m = build_map(pg_num=64)
        mapping = OSDMapMapping()
        mapping.update(m)
        total = sum(len(v) for v in mapping.by_osd.values())
        assert total == 64 * 3
        # each osd appears only in pgs that actually map to it
        for osd, pgs in mapping.by_osd.items():
            for pgid in pgs:
                assert osd in mapping.get(pgid)[2]


class TestObjectToPG:
    def test_distribution(self):
        m = build_map(pg_num=16)
        pool = m.pools[1]
        counts = [0] * 16
        for i in range(2000):
            raw = m.object_to_pg(1, "obj-%d" % i)
            pg = pool.raw_pg_to_pg(raw)
            counts[pg.ps] += 1
        assert min(counts) > 0
        assert max(counts) < 2000 / 16 * 2.5
