"""Cross-op device-call coalescing (osd/tpu_dispatch.py).

The dispatcher batches concurrent EC codec calls sharing a generator
(or decode matrix) into single device dispatches — the Python twin of
native/src/tpu_bridge.cc, shadowing the per-op entry at
src/osd/ECBackend.cc:1437. Results must be bit-exact and the dispatch
count measurably below the op count.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.osd.tpu_dispatch import TpuDispatcher

PROFILE = {"technique": "reed_sol_van", "k": "4", "m": "2", "w": "8"}


@pytest.fixture()
def dispatcher():
    # generous window: on a loaded 1-core box thread start latency can
    # exceed a tight delay, splitting batches and flaking exact-count
    # assertions
    d = TpuDispatcher(max_batch=8, max_delay=0.5)
    yield d
    d.shutdown()


def _codec():
    return registry.factory("jax_tpu", dict(PROFILE))


class TestCoalescing:
    def test_concurrent_encodes_fuse_and_stay_bit_exact(self, dispatcher):
        codec = _codec()
        rng = np.random.default_rng(1)
        batches = [rng.integers(0, 256, size=(3, 4, 512), dtype=np.uint8)
                   for _ in range(8)]
        direct = [np.asarray(codec.encode_batch(b)) for b in batches]
        outs = [None] * 8

        def worker(i):
            outs[i] = np.asarray(dispatcher.encode(codec, batches[i]))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for i in range(8):
            assert np.array_equal(outs[i], direct[i]), i
        assert dispatcher.stats["ops"] == 8
        assert dispatcher.stats["dispatches"] < 8
        assert dispatcher.stats["coalesced"] > 0

    def test_distinct_codec_instances_same_profile_coalesce(self,
                                                            dispatcher):
        """Every PG backend holds its own codec instance; identity is
        by VALUE (generator bitmatrix), so cross-PG ops still fuse."""
        c1, c2 = _codec(), _codec()
        assert c1 is not c2
        rng = np.random.default_rng(2)
        b1 = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        b2 = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        res = {}

        def w(tag, c, b):
            res[tag] = np.asarray(dispatcher.encode(c, b))

        t1 = threading.Thread(target=w, args=("a", c1, b1))
        t2 = threading.Thread(target=w, args=("b", c2, b2))
        t1.start(); t2.start(); t1.join(30); t2.join(30)
        assert np.array_equal(res["a"], np.asarray(c1.encode_batch(b1)))
        assert np.array_equal(res["b"], np.asarray(c1.encode_batch(b2)))
        # <= 2 tolerates a straggler thread missing the window under
        # extreme load; the by-value codec key is what is under test
        assert dispatcher.stats["dispatches"] <= 2

    def test_varying_stripe_counts_concatenate(self, dispatcher):
        """Ops with different stripe counts (same per-stripe shape)
        concatenate along axis 0."""
        codec = _codec()
        rng = np.random.default_rng(3)
        batches = [rng.integers(0, 256, size=(s, 4, 512), dtype=np.uint8)
                   for s in (1, 4, 2)]
        outs = [None] * 3

        def worker(i):
            outs[i] = np.asarray(dispatcher.encode(codec, batches[i]))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for i, b in enumerate(batches):
            assert outs[i].shape == (b.shape[0], 2, 512)
            assert np.array_equal(outs[i],
                                  np.asarray(codec.encode_batch(b))), i

    def test_decode_coalesces_per_signature(self, dispatcher):
        codec = _codec()
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, parity], axis=1)
        avail = (0, 2, 3, 5)
        chunks = full[:, list(avail), :]
        res = {}

        def w(tag):
            res[tag] = np.asarray(
                dispatcher.decode(codec, avail, chunks))

        t1 = threading.Thread(target=w, args=("a",))
        t2 = threading.Thread(target=w, args=("b",))
        t1.start(); t2.start(); t1.join(30); t2.join(30)
        assert np.array_equal(res["a"], full)
        assert np.array_equal(res["b"], full)
        assert dispatcher.stats["dispatches"] <= 2

    def test_error_propagates_to_every_submitter(self, dispatcher):
        class Boom:
            _bitmat = None

            def encode_batch(self, b):
                raise RuntimeError("device on fire")

        codec = Boom()
        errs = []

        def w():
            try:
                dispatcher.encode(codec, np.zeros((1, 2, 64), np.uint8))
            except RuntimeError as e:
                errs.append(str(e))

        threads = [threading.Thread(target=w) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert errs == ["device on fire"] * 3


class TestOsdIntegration:
    def test_concurrent_ec_writes_need_fewer_dispatches(self):
        """End to end: N concurrent EC writes through the cluster
        complete bit-exact with measurably fewer device dispatches
        than ops (the SURVEY §7 step-3 queue)."""
        from .cluster_util import MiniCluster
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02,
                "osd_tpu_coalesce_max_delay_ms": 15.0,
                "osd_tpu_coalesce_max_batch": 8}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(
                client, "coalesce",
                {"plugin": "jax_tpu", "technique": "reed_sol_van",
                 "k": "2", "m": "1", "w": "8"}, pg_num=8)
            ioctx = client.open_ioctx("coalesce")
            payloads = {("obj-%d" % i): (b"%02d" % i) * 2048
                        for i in range(16)}
            errs: list = []

            def writer(oid, data):
                try:
                    ioctx.write_full(oid, data, timeout=60)
                except Exception as e:
                    errs.append(e)

            threads = [threading.Thread(target=writer, args=(o, d))
                       for o, d in payloads.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errs, errs
            for oid, data in payloads.items():
                assert ioctx.read(oid) == data, oid
            ops = sum(o.tpu_dispatcher.stats["ops"]
                      for o in cluster.osds.values()
                      if o.tpu_dispatcher)
            dispatches = sum(o.tpu_dispatcher.stats["dispatches"]
                             for o in cluster.osds.values()
                             if o.tpu_dispatcher)
            assert ops >= 16
            assert dispatches < ops, (dispatches, ops)
        finally:
            cluster.stop()
