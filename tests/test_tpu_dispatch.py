"""Cross-op device-call coalescing (osd/tpu_dispatch.py).

The dispatcher batches concurrent EC codec calls sharing a generator
(or decode matrix) into single device dispatches — the Python twin of
native/src/tpu_bridge.cc, shadowing the per-op entry at
src/osd/ECBackend.cc:1437. Results must be bit-exact and the dispatch
count measurably below the op count.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.osd.tpu_dispatch import TpuDispatcher

PROFILE = {"technique": "reed_sol_van", "k": "4", "m": "2", "w": "8"}


@pytest.fixture()
def dispatcher():
    # generous window: on a loaded 1-core box thread start latency can
    # exceed a tight delay, splitting batches and flaking exact-count
    # assertions
    d = TpuDispatcher(max_batch=8, max_delay=0.5)
    yield d
    d.shutdown()


def _codec():
    return registry.factory("jax_tpu", dict(PROFILE))


class TestCoalescing:
    def test_concurrent_encodes_fuse_and_stay_bit_exact(self, dispatcher):
        codec = _codec()
        rng = np.random.default_rng(1)
        batches = [rng.integers(0, 256, size=(3, 4, 512), dtype=np.uint8)
                   for _ in range(8)]
        direct = [np.asarray(codec.encode_batch(b)) for b in batches]
        outs = [None] * 8

        def worker(i):
            outs[i] = np.asarray(dispatcher.encode(codec, batches[i]))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for i in range(8):
            assert np.array_equal(outs[i], direct[i]), i
        assert dispatcher.stats["ops"] == 8
        assert dispatcher.stats["dispatches"] < 8
        assert dispatcher.stats["coalesced"] > 0

    def test_distinct_codec_instances_same_profile_coalesce(self,
                                                            dispatcher):
        """Every PG backend holds its own codec instance; identity is
        by VALUE (generator bitmatrix), so cross-PG ops still fuse."""
        c1, c2 = _codec(), _codec()
        assert c1 is not c2
        rng = np.random.default_rng(2)
        b1 = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        b2 = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        res = {}

        def w(tag, c, b):
            res[tag] = np.asarray(dispatcher.encode(c, b))

        t1 = threading.Thread(target=w, args=("a", c1, b1))
        t2 = threading.Thread(target=w, args=("b", c2, b2))
        t1.start(); t2.start(); t1.join(30); t2.join(30)
        assert np.array_equal(res["a"], np.asarray(c1.encode_batch(b1)))
        assert np.array_equal(res["b"], np.asarray(c1.encode_batch(b2)))
        # <= 2 tolerates a straggler thread missing the window under
        # extreme load; the by-value codec key is what is under test
        assert dispatcher.stats["dispatches"] <= 2

    def test_varying_stripe_counts_concatenate(self, dispatcher):
        """Ops with different stripe counts (same per-stripe shape)
        concatenate along axis 0."""
        codec = _codec()
        rng = np.random.default_rng(3)
        batches = [rng.integers(0, 256, size=(s, 4, 512), dtype=np.uint8)
                   for s in (1, 4, 2)]
        outs = [None] * 3

        def worker(i):
            outs[i] = np.asarray(dispatcher.encode(codec, batches[i]))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for i, b in enumerate(batches):
            assert outs[i].shape == (b.shape[0], 2, 512)
            assert np.array_equal(outs[i],
                                  np.asarray(codec.encode_batch(b))), i

    def test_decode_coalesces_per_signature(self, dispatcher):
        codec = _codec()
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, parity], axis=1)
        avail = (0, 2, 3, 5)
        chunks = full[:, list(avail), :]
        res = {}

        def w(tag):
            res[tag] = np.asarray(
                dispatcher.decode(codec, avail, chunks))

        t1 = threading.Thread(target=w, args=("a",))
        t2 = threading.Thread(target=w, args=("b",))
        t1.start(); t2.start(); t1.join(30); t2.join(30)
        assert np.array_equal(res["a"], full)
        assert np.array_equal(res["b"], full)
        assert dispatcher.stats["dispatches"] <= 2

    def test_error_propagates_to_every_submitter(self, dispatcher):
        class Boom:
            _bitmat = None

            def encode_batch(self, b):
                raise RuntimeError("device on fire")

        codec = Boom()
        errs = []

        def w():
            try:
                dispatcher.encode(codec, np.zeros((1, 2, 64), np.uint8))
            except RuntimeError as e:
                errs.append(str(e))

        threads = [threading.Thread(target=w) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert errs == ["device on fire"] * 3


class TestOsdIntegration:
    def test_concurrent_ec_writes_need_fewer_dispatches(self):
        """End to end: N concurrent EC writes through the cluster
        complete bit-exact with measurably fewer device dispatches
        than ops (the SURVEY §7 step-3 queue)."""
        from .cluster_util import MiniCluster
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02,
                "osd_tpu_coalesce_max_delay_ms": 15.0,
                "osd_tpu_coalesce_max_batch": 8,
                # this row prices the classic coalescing queue; the
                # fused write transform never coalesces (per-object
                # compress decision + crc chains) and is priced in
                # test_fused_transform
                "osd_fused_transform": False}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(
                client, "coalesce",
                {"plugin": "jax_tpu", "technique": "reed_sol_van",
                 "k": "2", "m": "1", "w": "8"}, pg_num=8)
            ioctx = client.open_ioctx("coalesce")
            payloads = {("obj-%d" % i): (b"%02d" % i) * 2048
                        for i in range(16)}
            errs: list = []

            def writer(oid, data):
                try:
                    ioctx.write_full(oid, data, timeout=60)
                except Exception as e:
                    errs.append(e)

            threads = [threading.Thread(target=writer, args=(o, d))
                       for o, d in payloads.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errs, errs
            for oid, data in payloads.items():
                assert ioctx.read(oid) == data, oid
            ops = sum(o.tpu_dispatcher.stats["ops"]
                      for o in cluster.osds.values()
                      if o.tpu_dispatcher)
            dispatches = sum(o.tpu_dispatcher.stats["dispatches"]
                             for o in cluster.osds.values()
                             if o.tpu_dispatcher)
            assert ops >= 16
            assert dispatches < ops, (dispatches, ops)
        finally:
            cluster.stop()


class _FakeDevOps:
    """Deterministic fake device: records the order h2d/compute legs
    are ISSUED in and lets the test hold the compute stage closed, so
    'h2d of batch n+1 runs before compute of batch n completes' is an
    assertion, not a race."""

    def __init__(self):
        self.lock = threading.Lock()
        self.events = []             # ("h2d" | "compute", seq)
        self.h2d_count = 0
        self.compute_count = 0
        self.second_h2d_issued = threading.Event()
        self.compute_gate = threading.Event()   # test opens this

    def h2d(self, host):
        with self.lock:
            self.h2d_count += 1
            self.events.append(("h2d", self.h2d_count))
            if self.h2d_count >= 2:
                self.second_h2d_issued.set()
        return host

    def run(self, fn, x):
        self.compute_gate.wait(10)
        with self.lock:
            self.compute_count += 1
            self.events.append(("compute", self.compute_count))
        return fn(x)

    def d2h(self, out):
        return np.asarray(out)


class TestPipeline:
    """The overlapped depth-N dispatcher (ROADMAP direction A): h2d of
    batch n+1 concurrent with compute of n and d2h of n-1, future API,
    donation safety, strict per-batch error isolation."""

    def test_submit_async_future_api(self):
        d = TpuDispatcher(max_batch=4, max_delay=0.001,
                          pipeline_depth=2)
        try:
            codec = _codec()
            rng = np.random.default_rng(10)
            batch = rng.integers(0, 256, size=(2, 4, 512),
                                 dtype=np.uint8)
            fut = d.encode_async(codec, batch)
            out = fut.result(30)
            assert fut.done() and fut.exception() is None
            assert np.array_equal(out, np.asarray(
                codec.encode_batch(batch)))
        finally:
            d.shutdown()

    def test_concurrent_submitter_slicing_integrity(self):
        """Many submitters with DIFFERENT stripe counts fused through
        the pipeline: every submitter gets exactly its slice back,
        bit-exact, regardless of how the collector grouped them."""
        d = TpuDispatcher(max_batch=8, max_delay=0.05,
                          pipeline_depth=3)
        try:
            codec = _codec()
            rng = np.random.default_rng(11)
            sizes = [1, 4, 2, 3, 1, 5, 2, 1, 3, 4, 2, 1]
            batches = [rng.integers(0, 256, size=(s, 4, 512),
                                    dtype=np.uint8) for s in sizes]
            direct = [np.asarray(codec.encode_batch(b))
                      for b in batches]
            outs = [None] * len(batches)

            def worker(i):
                outs[i] = np.asarray(d.encode(codec, batches[i]))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(batches))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            for i in range(len(batches)):
                assert outs[i].shape == direct[i].shape, i
                assert np.array_equal(outs[i], direct[i]), i
        finally:
            d.shutdown()

    def test_per_batch_error_isolation(self):
        """A failed stage fails ONLY its batch's submitters; batches
        behind it keep flowing through the pipeline."""
        class Boom:
            _bitmat = None

            def encode_batch(self, b):
                raise RuntimeError("stage on fire")

        d = TpuDispatcher(max_batch=8, max_delay=0.001,
                          pipeline_depth=2)
        try:
            codec = _codec()
            rng = np.random.default_rng(12)
            good_batch = rng.integers(0, 256, size=(2, 4, 512),
                                      dtype=np.uint8)
            bad = d.encode_async(Boom(), np.zeros((1, 2, 64),
                                                  np.uint8))
            good = d.encode_async(codec, good_batch)
            with pytest.raises(RuntimeError, match="stage on fire"):
                bad.result(30)
            # the batch behind the failed one completes normally
            assert np.array_equal(
                np.asarray(good.result(30)),
                np.asarray(codec.encode_batch(good_batch)))
            # and the dispatcher is still alive for new work
            again = d.encode(codec, good_batch)
            assert np.array_equal(np.asarray(again),
                                  np.asarray(
                                      codec.encode_batch(good_batch)))
        finally:
            d.shutdown()

    def test_donation_safety_host_array_intact(self):
        """Donation (when active) only ever consumes the dispatcher's
        PRIVATE staged device buffer — a submitter's host array is
        untouched and reusable after the call."""
        d = TpuDispatcher(max_batch=4, max_delay=0.001,
                          pipeline_depth=2)
        try:
            codec = _codec()
            rng = np.random.default_rng(13)
            batch = rng.integers(0, 256, size=(3, 4, 512),
                                 dtype=np.uint8)
            before = batch.tobytes()
            out1 = np.asarray(d.encode(codec, batch))
            assert batch.tobytes() == before      # no use-after-donate
            # the SAME host array resubmitted produces the same parity
            out2 = np.asarray(d.encode(codec, batch))
            assert np.array_equal(out1, out2)
        finally:
            d.shutdown()

    def test_fake_device_h2d_overlaps_compute(self):
        """Deterministic overlap proof: with the compute stage held
        closed, the h2d stage still stages the NEXT batch — h2d(n+1)
        is issued before compute(n) completes."""
        d = TpuDispatcher(max_batch=1, max_delay=0.0,
                          pipeline_depth=2)
        fake = _FakeDevOps()
        d._devops = fake
        d._donate_ok = False          # route through the plain fn path
        try:
            codec = _codec()
            rng = np.random.default_rng(14)
            b1 = rng.integers(0, 256, size=(1, 4, 512), dtype=np.uint8)
            b2 = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
            f1 = d.encode_async(codec, b1)
            f2 = d.encode_async(codec, b2)
            # compute(1) is blocked on the gate; the pipeline must
            # still issue h2d(2) — THE overlap this PR exists for
            assert fake.second_h2d_issued.wait(10), \
                "h2d of batch 2 never issued while compute(1) pending"
            assert fake.compute_count == 0        # compute(1) not done
            fake.compute_gate.set()
            out1, out2 = f1.result(30), f2.result(30)
            assert np.array_equal(np.asarray(out1), np.asarray(
                codec.encode_batch(b1)))
            assert np.array_equal(np.asarray(out2), np.asarray(
                codec.encode_batch(b2)))
            # issue order on the fake device: second h2d before the
            # first compute retires
            assert fake.events.index(("h2d", 2)) \
                < fake.events.index(("compute", 1))
        finally:
            fake.compute_gate.set()
            d.shutdown()

    def test_stage_intervals_recorded_and_status_shape(self):
        """Pipelined dispatches record real stage intervals into the
        l_tpu_* counters (free instrumentation) and `dispatch status`
        reports the ring."""
        d = TpuDispatcher(max_batch=4, max_delay=0.001,
                          pipeline_depth=2)
        try:
            codec = _codec()
            rng = np.random.default_rng(15)
            for _ in range(3):
                d.encode(codec, rng.integers(0, 256, size=(2, 4, 512),
                                             dtype=np.uint8))
            dump = d.perf.dump()
            assert dump["l_tpu_h2d"]["avgcount"] >= 1
            assert dump["l_tpu_compute"]["avgcount"] >= 1
            assert dump["l_tpu_d2h"]["avgcount"] >= 1
            status = d.dispatch_status()
            assert status["pipeline_depth"] == 2
            assert status["overlapped"] is True
            assert set(status["ring"]) == {"staging", "computing",
                                           "draining"}
            assert status["dispatches"] >= 1
            assert "segments_s" in status
        finally:
            d.shutdown()

    def test_depth_one_keeps_legacy_synchronous_path(self):
        """pipeline_depth=1 is the historical coalesce-then-block
        loop: correct results, no stage threads, no segment samples
        without a tracer."""
        d = TpuDispatcher(max_batch=4, max_delay=0.001,
                          pipeline_depth=1)
        try:
            codec = _codec()
            rng = np.random.default_rng(16)
            batch = rng.integers(0, 256, size=(2, 4, 512),
                                 dtype=np.uint8)
            out = np.asarray(d.encode(codec, batch))
            assert np.array_equal(out, np.asarray(
                codec.encode_batch(batch)))
            assert d.perf.dump()["l_tpu_h2d"]["avgcount"] == 0
            assert d.dispatch_status()["overlapped"] is False
        finally:
            d.shutdown()

class _SleepyDevOps:
    """Deterministic fake device with a configurable latency per stage,
    so the test can make ANY stage the pipeline's bottleneck and assert
    the profiler names it."""

    def __init__(self, h2d_s=0.0, compute_s=0.0, d2h_s=0.0):
        self.h2d_s, self.compute_s, self.d2h_s = h2d_s, compute_s, d2h_s

    def h2d(self, host):
        if self.h2d_s:
            time.sleep(self.h2d_s)
        return host

    def run(self, fn, x):
        if self.compute_s:
            time.sleep(self.compute_s)
        return fn(x)

    def d2h(self, out):
        if self.d2h_s:
            time.sleep(self.d2h_s)
        return np.asarray(out)


class TestStallAttribution:
    """`dispatch profile` stall attribution: make each stage the
    bottleneck in turn on a deterministic fake device and assert the
    verdict names the correct stage with majority attribution."""

    def _profile_with(self, devops, n=12, submit_gap=0.0):
        d = TpuDispatcher(max_batch=1, max_delay=0.0, pipeline_depth=2)
        d._devops = devops
        d._donate_ok = False
        try:
            codec = _codec()
            rng = np.random.default_rng(21)
            batches = [rng.integers(0, 256, size=(1, 4, 256),
                                    dtype=np.uint8) for _ in range(n)]
            # warm the codec's jit outside the profiled window: the
            # one-time trace/compile would otherwise dominate compute
            d.encode(codec, batches[0])
            d.profile_reset()
            futs = []
            for b in batches:
                futs.append(d.encode_async(codec, b))
                if submit_gap:
                    time.sleep(submit_gap)
            for f in futs:
                f.result(60)
            return d.dispatch_profile()
        finally:
            d.shutdown()

    def test_slow_h2d_is_h2d_bound(self):
        prof = self._profile_with(_SleepyDevOps(h2d_s=0.03))
        assert prof["bound"] == "h2d", prof
        assert prof["attribution"] >= 0.5, prof
        assert prof["verdict"].startswith("h2d-bound"), prof

    def test_slow_compute_is_compute_bound(self):
        prof = self._profile_with(_SleepyDevOps(compute_s=0.03))
        assert prof["bound"] == "compute", prof
        assert prof["attribution"] >= 0.5, prof
        assert prof["verdict"].startswith("compute-bound"), prof

    def test_slow_d2h_is_d2h_bound(self):
        prof = self._profile_with(_SleepyDevOps(d2h_s=0.03))
        assert prof["bound"] == "d2h", prof
        assert prof["attribution"] >= 0.5, prof
        assert prof["verdict"].startswith("d2h-bound"), prof

    def test_slow_submitters_are_collector_starved(self):
        """Fast device + trickling submitters: the device is NOT the
        wall and the verdict must say so instead of blaming a stage."""
        prof = self._profile_with(_SleepyDevOps(), n=10,
                                  submit_gap=0.03)
        assert prof["bound"] == "collector", prof
        assert prof["attribution"] >= 0.5, prof
        assert prof["verdict"].startswith("collector-starved"), prof

    def test_profile_shape_and_reset(self):
        d = TpuDispatcher(max_batch=4, max_delay=0.001,
                          pipeline_depth=2)
        try:
            codec = _codec()
            rng = np.random.default_rng(22)
            d.encode(codec, rng.integers(0, 256, size=(2, 4, 256),
                                         dtype=np.uint8))
            prof = d.dispatch_profile()
            assert set(prof) == {"window_s", "verdict", "bound",
                                 "attribution", "stages",
                                 "queue_occupancy_avg"}
            for stage in ("collector", "h2d", "compute", "d2h"):
                row = prof["stages"][stage]
                for state in ("busy", "idle", "blocked"):
                    assert 0.0 <= row[state + "_frac"] <= 1.0
            # the stage counters ride the perf dump for MMgrReport
            dump = d.perf.dump()
            assert "l_tpu_stage_h2d_busy" in dump
            assert "l_tpu_stage_collector_idle" in dump
            # reset restarts the window
            d.profile_reset()
            prof2 = d.dispatch_profile()
            assert prof2["window_s"] < prof["window_s"] + 0.5
            assert prof2["stages"]["h2d"]["busy_s"] <= \
                prof["stages"]["h2d"]["busy_s"] + 1e-6
        finally:
            d.shutdown()
