"""Messenger: ordered delivery, dispatcher chain, reconnect, injection."""

import threading
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.msg.message import MOSDOp, MPing, MPingReply
from ceph_tpu.msg.messenger import Dispatcher, Messenger


class Collector(Dispatcher):
    def __init__(self, types=None):
        self.got = []
        self.resets = []
        self.event = threading.Event()
        self.types = types

    def ms_dispatch(self, msg):
        if self.types is not None and msg.get_type() not in self.types:
            return False
        self.got.append(msg)
        self.event.set()
        return True

    def ms_handle_reset(self, addr):
        self.resets.append(addr)

    def wait_for(self, n, timeout=5.0):
        deadline = time.monotonic() + timeout
        while len(self.got) < n and time.monotonic() < deadline:
            time.sleep(0.005)
        return len(self.got) >= n


def make_pair():
    a, b = Messenger(("a", 0)), Messenger(("b", 0))
    a.start()
    b.start()
    return a, b


class TestMessenger:
    def test_send_and_dispatch(self):
        a, b = make_pair()
        try:
            coll = Collector()
            b.add_dispatcher_tail(coll)
            a.send_message(MPing(stamp=1.5), b.my_addr)
            assert coll.wait_for(1)
            msg = coll.got[0]
            assert msg.get_type() == "MPing"
            assert msg.stamp == 1.5
            assert msg.from_name == ("a", 0)
        finally:
            a.shutdown()
            b.shutdown()

    def test_ordered_delivery(self):
        a, b = make_pair()
        try:
            coll = Collector()
            b.add_dispatcher_tail(coll)
            for i in range(200):
                a.send_message(MOSDOp(tid=i), b.my_addr)
            assert coll.wait_for(200)
            assert [m.tid for m in coll.got] == list(range(200))
        finally:
            a.shutdown()
            b.shutdown()

    def test_dispatcher_chain_first_taker(self):
        a, b = make_pair()
        try:
            pings = Collector(types={"MPing"})
            rest = Collector()
            b.add_dispatcher_head(pings)
            b.add_dispatcher_tail(rest)
            a.send_message(MPing(), b.my_addr)
            a.send_message(MOSDOp(tid=7), b.my_addr)
            assert pings.wait_for(1) and rest.wait_for(1)
            assert [m.get_type() for m in pings.got] == ["MPing"]
            assert [m.get_type() for m in rest.got] == ["MOSDOp"]
        finally:
            a.shutdown()
            b.shutdown()

    def test_bidirectional_reply(self):
        a, b = make_pair()
        try:
            got_reply = Collector(types={"MPingReply"})
            a.add_dispatcher_tail(got_reply)

            class Responder(Dispatcher):
                def ms_dispatch(self, msg):
                    if msg.get_type() == "MPing":
                        b.send_message(MPingReply(stamp=msg.stamp),
                                       a.my_addr)
                        return True
                    return False

            b.add_dispatcher_tail(Responder())
            a.send_message(MPing(stamp=9.0), b.my_addr)
            assert got_reply.wait_for(1)
            assert got_reply.got[0].stamp == 9.0
        finally:
            a.shutdown()
            b.shutdown()

    def test_lossless_reconnect_resends(self):
        """Messages queued while the peer is down arrive once it binds
        (lossless policy: reconnect + resend, AsyncConnection analog)."""
        a = Messenger(("a", 0))
        a.start()
        try:
            # send to an address nobody owns yet
            import socket as pysock
            probe = pysock.socket()
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
            probe.close()
            target = ("127.0.0.1", port)
            a.send_message(MPing(stamp=4.2), target)
            time.sleep(0.2)
            b = Messenger(("b", 0))
            b.bind("127.0.0.1", port)
            coll = Collector()
            b.add_dispatcher_tail(coll)
            b.start()
            try:
                assert coll.wait_for(1)
                assert coll.got[0].stamp == 4.2
            finally:
                b.shutdown()
        finally:
            a.shutdown()

    def test_reconnect_resend_not_redelivered(self):
        """Exactly-once for dispatchers: a resend whose MSGACK was
        lost in the pipe death is acked again but NOT re-dispatched
        (the reference's in_seq dedup across reconnects)."""
        a, b = make_pair()
        try:
            coll = Collector()
            b.add_dispatcher_tail(coll)
            m = MPing(stamp=7.7)
            a.send_message(m, b.my_addr)
            assert coll.wait_for(1)
            conn = a._conns[b.my_addr]
            # let the MSGACK trim land, then simulate the LOST-ack
            # case: the delivered message back in the resend set
            deadline = time.monotonic() + 5
            while conn._unacked and time.monotonic() < deadline:
                time.sleep(0.005)
            assert not conn._unacked
            with conn.lock:
                conn._unacked.append((conn.out_seq, m))
            sock = conn.sock
            conn.sock = None
            sock.close()
            a.send_message(MPing(stamp=8.8), b.my_addr)
            assert coll.wait_for(2)
            time.sleep(0.3)   # window for a wrong redelivery
            stamps = [g.stamp for g in coll.got]
            assert stamps.count(7.7) == 1, stamps
            assert stamps.count(8.8) == 1, stamps
        finally:
            a.shutdown()
            b.shutdown()

    def test_lossy_drops_on_failure(self):
        conf = Config()
        a = Messenger(("client", 1), conf=conf, policy_lossy=True)
        a.start()
        try:
            reset = Collector()
            a.add_dispatcher_tail(reset)
            a.send_message(MPing(), ("127.0.0.1", 1))  # nothing there
            deadline = time.monotonic() + 5
            while not reset.resets and time.monotonic() < deadline:
                time.sleep(0.01)
            assert reset.resets  # ms_handle_reset fired
        finally:
            a.shutdown()

    def test_injection_drops_messages(self):
        conf = Config({"ms_inject_socket_failures": 2})
        a = Messenger(("a", 0), conf=conf)
        b = Messenger(("b", 0))
        a.start()
        b.start()
        try:
            coll = Collector()
            b.add_dispatcher_tail(coll)
            for i in range(100):
                a.send_message(MOSDOp(tid=i), b.my_addr)
            time.sleep(1.0)
            # roughly half dropped; definitely some, definitely not all
            assert 0 < len(coll.got) < 100
            # order of survivors is preserved
            tids = [m.tid for m in coll.got]
            assert tids == sorted(tids)
        finally:
            a.shutdown()
            b.shutdown()

    def test_mark_down(self):
        a, b = make_pair()
        try:
            coll = Collector()
            b.add_dispatcher_tail(coll)
            a.send_message(MPing(), b.my_addr)
            assert coll.wait_for(1)
            a.mark_down(b.my_addr)
            a.send_message(MPing(), b.my_addr)  # new connection forms
            assert coll.wait_for(2)
        finally:
            a.shutdown()
            b.shutdown()
