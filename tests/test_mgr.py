"""Mgr daemon + module tests.

Models the reference's mgr behavior (src/mgr/, src/pybind/mgr/):
daemon reports folding into DaemonState, module notify fan-out,
command routing, the prometheus exposition format, and the status
module — against a live in-process cluster.
"""

import time
import urllib.request

import pytest

from ceph_tpu.mgr import (DaemonStateIndex, MgrDaemon, MgrModule,
                          PrometheusModule, StatusModule)

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


class TestDaemonState:
    def test_report_and_staleness(self):
        idx = DaemonStateIndex(stale_after=0.05)
        idx.report("osd.0", {"osd": {"op": 5}}, {"host": "a"})
        assert idx.get_perf("osd.0") == {"osd": {"op": 5}}
        assert idx.get_metadata("osd.0") == {"host": "a"}
        assert not idx.is_stale("osd.0")
        time.sleep(0.08)
        assert idx.is_stale("osd.0")
        assert idx.names(include_stale=False) == []
        assert idx.names() == ["osd.0"]
        idx.report("osd.0", {"osd": {"op": 6}})
        assert idx.all_perf() == {"osd.0": {"osd": {"op": 6}}}


class TestModuleHost:
    def test_notify_health_and_commands(self):
        mgr = MgrDaemon.__new__(MgrDaemon)  # host-only, no network
        from ceph_tpu.mgr.daemon_state import DaemonStateIndex as DSI
        import threading
        mgr.daemon_state = DSI()
        mgr.modules = {}
        mgr.health = {}
        mgr._lock = threading.Lock()
        mgr.osdmap = None
        events = []

        class Probe(MgrModule):
            COMMANDS = [{"cmd": "probe ping", "desc": ""}]

            def notify(self, t, i):
                events.append((t, i))

            def handle_command(self, cmd):
                return 0, "pong", ""

        mod = mgr.register_module(Probe)
        mgr._notify_all("osd_map", 42)
        assert events == [("osd_map", 42)]
        assert mgr.module_command({"prefix": "probe ping"}) == \
            (0, "pong", "")
        assert mgr.module_command({"prefix": "nope"})[0] == -22
        mod.set_health_checks({"PROBE_WARN": {
            "severity": "warning", "summary": "s", "detail": []}})
        assert "PROBE_WARN" in mgr.get_state("health")
        mod.set_health_checks({})
        assert mgr.get_state("health") == {}


@pytest.fixture(scope="module")
def mgr_cluster():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    mgr = MgrDaemon(cluster.monmap)
    mgr.init()
    for osd in cluster.osds.values():
        osd.mgr_addr = mgr.addr
    client = cluster.client()
    cluster.create_replicated_pool(client, "mgrd", size=2, pg_num=4)
    io = client.open_ioctx("mgrd")
    for i in range(5):
        io.write_full("obj%d" % i, b"x" * 1000)
    # the mgr self-reports through the same pipeline, so count only
    # the OSD reporters
    assert wait_until(
        lambda: sum(n.startswith("osd.") for n in
                    mgr.daemon_state.names(include_stale=False)) == 3,
        timeout=10), "osd reports never arrived"
    # ... and for the mgr's SUBSCRIBED map to catch up to all three
    # boots: under a loaded host the first delivered epoch can predate
    # the last osd's mark-up, and prometheus renders from this cache
    assert wait_until(
        lambda: mgr.osdmap is not None
        and sum(mgr.osdmap.is_up(o)
                for o in range(mgr.osdmap.max_osd)) == 3,
        timeout=10)
    yield cluster, mgr
    mgr.shutdown()
    cluster.stop()


class TestLiveMgr:
    def test_reports_carry_op_counters(self, mgr_cluster):
        _, mgr = mgr_cluster

        def total_ops():
            return sum(
                perf.get("osd", {}).get("op", 0)
                for perf in mgr.daemon_state.all_perf(
                    include_stale=True).values())

        # reports are periodic snapshots; wait for one taken after the
        # fixture's writes
        assert wait_until(lambda: total_ops() >= 5, timeout=10), \
            total_ops()

    def test_prometheus_render(self, mgr_cluster):
        _, mgr = mgr_cluster
        prom = mgr.register_module(PrometheusModule)
        text = prom.render()
        assert "ceph_osdmap_epoch" in text
        assert 'ceph_osd_up{ceph_daemon="osd.0"} 1.0' in text
        assert "ceph_num_osd_in 3.0" in text
        assert "ceph_pool_pg_num" in text
        assert "ceph_osd_osd_op{" in text          # per-daemon counter
        rc, out, err = mgr.module_command({"prefix": "prometheus metrics"})
        assert rc == 0 and "ceph_osd_up" in out

    def test_prometheus_http_endpoint(self, mgr_cluster):
        _, mgr = mgr_cluster
        prom = mgr.modules.get("prometheus") or \
            mgr.register_module(PrometheusModule)
        host, port = prom.serve_http()
        try:
            body = urllib.request.urlopen(
                "http://%s:%d/metrics" % (host, port),
                timeout=5).read().decode()
            assert "ceph_osd_up" in body
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    "http://%s:%d/bogus" % (host, port), timeout=5)
        finally:
            prom.shutdown()

    def test_status_module(self, mgr_cluster):
        _, mgr = mgr_cluster
        status = mgr.register_module(StatusModule)
        rc, out, _ = mgr.module_command({"prefix": "osd status"})
        assert rc == 0
        assert "0\tup\tin" in out
        assert out.count("yes") == 3   # all three report to the mgr
        rc, out, _ = mgr.module_command({"prefix": "status"})
        assert rc == 0
        assert "3 up, 3 in" in out
        assert "HEALTH_OK" in out


class TestBalancerModule:
    def test_optimize_applies_through_mon(self, mgr_cluster):
        """End-to-end balancer round: skew the map with hand-seeded
        pg_upmap_items, run `balancer optimize`, and watch the
        monitor-published map flatten — from the CLIENT's view, not
        just the mgr's."""
        from ceph_tpu.mgr import BalancerModule
        from ceph_tpu.osd.balancer import eval_distribution
        cluster, mgr = mgr_cluster
        client = cluster.client()
        cluster.create_replicated_pool(client, "baltest", size=2,
                                       pg_num=32)
        assert wait_until(
            lambda: any(p.name == "baltest"
                        for p in mgr.osdmap.pools.values()),
            timeout=10)
        pool_id = next(p.pool_id for p in mgr.osdmap.pools.values()
                       if p.name == "baltest")
        # seed skew: shove replicas from osd 1 onto osd 0
        from ceph_tpu.osd.osd_map import OSDMapMapping
        mapping = OSDMapMapping()
        mapping.update(mgr.osdmap.clone(), batched=False)
        seeded = 0
        for pgid, (up, _, _, _) in sorted(
                mapping.by_pg.items(),
                key=lambda kv: (kv[0].pool, kv[0].ps)):
            if pgid.pool != pool_id or seeded >= 8:
                continue
            if 1 in up and 0 not in up:
                r, _, _ = client.mon_command({
                    "prefix": "osd pg-upmap-items",
                    "pgid": [pgid.pool, pgid.ps],
                    "mappings": [[1, 0]]})
                assert r == 0
                seeded += 1
        assert seeded >= 4
        assert wait_until(
            lambda: sum(1 for pg in mgr.osdmap.pg_upmap_items
                        if pg.pool == pool_id) >= seeded, timeout=10)
        before = eval_distribution(mgr.osdmap, pools={pool_id},
                                   use_device=False)
        assert before.deviation(0) >= 2
        bal = mgr.register_module(BalancerModule)
        bal.max_changes_per_round = 50
        rc, out, _ = mgr.module_command({"prefix": "balancer optimize"})
        assert rc == 0 and "applied" in out
        # the proposal flowed through paxos: the CLIENT's subscribed
        # map converges to a flatter distribution
        def client_flattened():
            m = client.osdmap
            if m is None or m.epoch <= mgr.osdmap.epoch - 5:
                return False
            d = eval_distribution(m, pools={pool_id}, use_device=False)
            return d.total_deviation < before.total_deviation and \
                abs(d.deviation(0)) < before.deviation(0)
        assert wait_until(client_flattened, timeout=15)
        rc, _, data = mgr.module_command({"prefix": "balancer status"})
        assert rc == 0 and data["last_optimize"]["applied"] > 0

    def test_eval_command(self, mgr_cluster):
        from ceph_tpu.mgr import BalancerModule
        _, mgr = mgr_cluster
        bal = mgr.modules.get("balancer") or \
            mgr.register_module(BalancerModule)
        rc, _, data = mgr.module_command({"prefix": "balancer eval"})
        assert rc == 0
        assert "stddev" in data and "pg_counts" in data

    def test_on_off(self, mgr_cluster):
        from ceph_tpu.mgr import BalancerModule
        _, mgr = mgr_cluster
        bal = mgr.modules.get("balancer") or \
            mgr.register_module(BalancerModule)
        rc, out, _ = mgr.module_command({"prefix": "balancer on"})
        assert rc == 0 and bal.active
        rc, _, data = mgr.module_command({"prefix": "balancer status"})
        assert data["active"] is True
        rc, out, _ = mgr.module_command({"prefix": "balancer off"})
        assert rc == 0 and not bal.active
