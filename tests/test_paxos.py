"""Full-Paxos recovery semantics under deterministic message control.

The scenarios VERDICT round 1 flagged as unproven in the collapsed
flow (reference behavior: src/mon/Paxos.cc collect/begin/accept/commit
+ lease machinery):

  - a leader dying between accept and commit must NOT lose the value:
    the next leader's collect finds it uncommitted on a survivor and
    re-proposes it
  - a partitioned quorum must never commit past a silent member
    (all-accept rule) and must never fork or lose a committed version
  - a stale leader's begin (lower pn) is ignored after a newer promise
  - promises and pending values survive a monitor restart (durable
    accepted_pn / uncommitted triple)
"""

from __future__ import annotations

from collections import deque

from ceph_tpu.mon.paxos import (Paxos, STATE_ACTIVE, STATE_RECOVERING,
                                STATE_UPDATING)
from ceph_tpu.store.kv import MemDB


class FakeElector:
    def __init__(self):
        self.restarts = 0

    def start(self):
        self.restarts += 1


class FakeMon:
    def __init__(self, rank, net, n):
        self.rank = rank
        self.net = net
        self.monmap = {i: i for i in range(n)}
        self.quorum: list = []
        self.state = "peon"
        self.elector = FakeElector()
        self.committed: list = []
        self.store = MemDB()
        self.paxos = Paxos(self, self.store)

    def is_leader(self):
        return self.state == "leader"

    def quorum_size(self):
        return len(self.monmap) // 2 + 1

    def peer_ranks(self):
        return [r for r in self.monmap if r != self.rank]

    def send_mon(self, rank, msg):
        msg.from_name = ("mon", self.rank)
        self.net.queue.append((self.rank, rank, msg))

    def _on_paxos_commit(self, version, value):
        self.committed.append((version, value))


class Net:
    """Manual message pump: full control over delivery and loss."""

    def __init__(self, n):
        self.queue: deque = deque()
        self.down: set = set()
        self.mons = [FakeMon(i, self, n) for i in range(n)]

    def make_leader(self, rank, quorum):
        for m in self.mons:
            if m.rank == rank:
                m.state = "leader"
                m.quorum = list(quorum)
                m.paxos.leader_init()
            elif m.rank in quorum:
                m.state = "peon"
                m.quorum = list(quorum)
                m.paxos.peon_init()

    def pump(self, drop=None, limit=1000):
        """Deliver queued messages until quiet. drop(src, dst, msg) ->
        True suppresses a message; down ranks never send or receive."""
        n = 0
        while self.queue and n < limit:
            src, dst, msg = self.queue.popleft()
            n += 1
            if src in self.down or dst in self.down:
                continue
            if drop is not None and drop(src, dst, msg):
                continue
            self.mons[dst].paxos.handle(msg)
        assert n < limit, "message storm"


class TestCollectRecovery:
    def test_leader_killed_between_accept_and_commit(self):
        """The canonical Paxos case: value accepted on peons, leader
        dies before commit — the chosen value must survive into the
        next reign."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()                         # collect/last round
        assert net.mons[0].paxos.state == STATE_ACTIVE

        net.mons[0].paxos.propose(b"precious")
        # deliver the begins to the peons, but swallow their accepts:
        # the leader dies without ever committing
        net.pump(drop=lambda s, d, m: m.op == "accept")
        assert net.mons[1].paxos.uncommitted_value == b"precious"
        assert net.mons[0].committed == []
        net.down.add(0)

        # new reign: mon.1 collects from mon.2, finds the uncommitted
        # value, re-proposes and commits it
        net.make_leader(1, [1, 2])
        net.pump()
        assert net.mons[1].committed == [(1, b"precious")]
        assert net.mons[2].committed == [(1, b"precious")]

    def test_uncommitted_on_single_survivor_still_wins(self):
        """Only ONE peon accepted before the leader died; the value
        must still be recovered (it might have been exposed)."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"v")
        # only mon.2 ever sees the begin; all accepts vanish
        net.pump(drop=lambda s, d, m: m.op == "accept"
                 or (m.op == "begin" and d == 1))
        assert net.mons[2].paxos.uncommitted_value == b"v"
        assert net.mons[1].paxos.uncommitted_value == b""
        net.down.add(0)

        net.make_leader(1, [1, 2])
        net.pump()
        assert net.mons[1].committed == [(1, b"v")]
        assert net.mons[2].committed == [(1, b"v")]

    def test_recovered_value_beats_new_queue(self):
        """A recovered uncommitted value commits BEFORE values queued
        in the new reign (same version slot can't be stolen)."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"old")
        net.pump(drop=lambda s, d, m: m.op == "accept")
        net.down.add(0)

        net.make_leader(1, [1, 2])
        net.mons[1].paxos.propose(b"new")   # queued during recovery
        net.pump()
        assert net.mons[1].committed == [(1, b"old"), (2, b"new")]
        assert net.mons[2].committed == [(1, b"old"), (2, b"new")]


class TestPartition:
    def test_no_commit_past_silent_member(self):
        """All-accept rule: with one quorum member unreachable the
        value must NOT commit, and the accept timeout forces a new
        election instead."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.down.add(2)
        lead = net.mons[0].paxos
        lead.ACCEPT_TIMEOUT = -1.0         # expire immediately
        net.mons[0].paxos.propose(b"x")
        net.pump()
        assert net.mons[0].committed == []
        assert lead.state == STATE_UPDATING
        lead.tick()
        assert net.mons[0].committed == []
        assert net.mons[0].elector.restarts == 1

        # re-elected without the dead peon: the value (persisted as
        # the leader's own uncommitted) commits on the smaller quorum
        net.make_leader(0, [0, 1])
        net.pump()
        assert net.mons[0].committed == [(1, b"x")]
        assert net.mons[1].committed == [(1, b"x")]

    def test_committed_versions_survive_partition_heal(self):
        """No committed version is ever lost or forked: the rejoining
        mon is caught up by the collect round."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"a")
        net.pump()
        net.down.add(2)
        net.make_leader(0, [0, 1])
        net.pump()
        net.mons[0].paxos.propose(b"b")
        net.pump()
        assert net.mons[0].committed == [(1, b"a"), (2, b"b")]
        assert net.mons[2].committed == [(1, b"a")]

        # heal: mon.2 rejoins; the next collect shares what it missed
        net.down.clear()
        net.make_leader(0, [0, 1, 2])
        net.pump()
        assert net.mons[2].committed == [(1, b"a"), (2, b"b")]
        # every store agrees on every committed version
        for v in (1, 2):
            vals = {bytes(m.store.get("paxos", "%016d" % v) or b"")
                    for m in net.mons}
            assert len(vals) == 1 and vals != {b""}


class TestStaleLeader:
    def test_lower_pn_begin_ignored(self):
        """A deposed leader's begin must not be accepted after the
        peons promised a higher pn."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        old_pn = net.mons[0].paxos.accepted_pn

        # a new reign raises the promised pn everywhere
        net.make_leader(1, [0, 1, 2])
        net.pump()
        assert net.mons[2].paxos.accepted_pn > old_pn

        # the deposed leader wakes up and begins with its stale pn
        net.mons[0].state = "leader"
        net.mons[0].quorum = [0, 1, 2]
        net.mons[0].paxos.state = STATE_ACTIVE
        net.mons[0].paxos.accepted_pn = old_pn
        net.mons[0].paxos.propose(b"stale")
        net.pump()
        assert all(m.committed == [] for m in net.mons)
        assert net.mons[2].paxos.uncommitted_value != b"stale"


class TestDurability:
    def test_promise_survives_restart(self):
        """accepted_pn and the uncommitted triple reload from the
        store: a restarted peon keeps its promises."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"keep")
        net.pump(drop=lambda s, d, m: m.op == "accept")
        peon = net.mons[1]
        pn = peon.paxos.accepted_pn
        assert peon.paxos.uncommitted_value == b"keep"

        # "restart": rebuild the Paxos instance over the same store
        peon.paxos = Paxos(peon, peon.store)
        assert peon.paxos.accepted_pn == pn
        assert peon.paxos.uncommitted_value == b"keep"
        assert peon.paxos.uncommitted_v == 1

    def test_single_mon_promotes_uncommitted_on_restart(self):
        net = Net(1)
        net.make_leader(0, [0])
        mon = net.mons[0]
        assert mon.paxos.state == STATE_ACTIVE
        mon.paxos.propose(b"solo")
        assert mon.committed == [(1, b"solo")]

        # crash mid-begin: fake a persisted uncommitted value
        batch = mon.store.get_transaction()
        batch.set("paxos", "uncommitted_pn", b"101")
        batch.set("paxos", "uncommitted_v", b"2")
        batch.set("paxos", "uncommitted_value", b"crashy")
        mon.store.submit_transaction(batch)
        mon.paxos = Paxos(mon, mon.store)
        mon.paxos.leader_init()
        assert (2, b"crashy") in mon.committed


class TestLease:
    def test_peon_readable_within_lease_only(self):
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"v")
        net.pump()                          # commit + lease fan-out
        assert net.mons[1].paxos.is_readable()
        assert net.mons[0].paxos.is_writeable()
        # expire the peon's lease
        net.mons[1].paxos.lease_until = 0.0
        assert not net.mons[1].paxos.is_readable()

    def test_fresh_peon_not_readable(self):
        net = Net(3)
        for m in net.mons:
            m.paxos.peon_init()
        assert not net.mons[1].paxos.is_readable()


class TestCommitGap:
    def test_dropped_commit_triggers_catchup(self):
        """A peon that misses one commit must not serve stale state
        forever: the next commit's higher last_committed triggers a
        catch-up request that backfills the hole."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        # commit v1, but mon.2 never hears about it
        net.mons[0].paxos.propose(b"a")
        net.pump(drop=lambda s, d, m: m.op == "commit" and d == 2)
        assert net.mons[2].committed == []
        # commit v2 normally: mon.2 sees the gap, asks, and backfills
        net.mons[0].paxos.propose(b"b")
        net.pump()
        assert net.mons[2].committed == [(1, b"a"), (2, b"b")]


class TestLeaderLeaseAuthority:
    def test_partitioned_ex_leader_goes_stale(self):
        """A leader whose quorum stops acking its leases must lose
        readability and step down for re-election — never serve stale
        reads on self-granted leases."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()                          # collect + lease + acks
        lead = net.mons[0].paxos
        assert lead.is_readable()

        # partition: peons unreachable, their lease acks never arrive
        net.down.update({1, 2})
        lead.LEASE_DURATION = 0.0           # current grant expires now
        lead._lease_ack_deadline = 1e-9     # ack window already blown
        lead.lease_until = 0.0
        assert not lead.is_readable()
        lead.tick()
        assert net.mons[0].elector.restarts == 1
        assert lead.state == STATE_RECOVERING

    def test_behind_peon_refuses_lease(self):
        """A peon that is missing commits acks the lease round but does
        not become readable, and asks for the missing range."""
        net = Net(3)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        # mon.2 misses the commit of v1 AND loses its catchup reply;
        # the next lease advertises last_committed=1
        net.mons[0].paxos.propose(b"a")
        net.pump(drop=lambda s, d, m:
                 (m.op == "commit" or m.op == "catchup") and 2 in (s, d))
        assert net.mons[2].committed == []
        stale = net.mons[2].paxos
        stale.lease_until = 0.0
        # a fresh lease arrives while still behind: no readability
        net.mons[0].paxos._extend_lease_locked()
        net.pump(drop=lambda s, d, m: m.op == "catchup")
        assert not stale.is_readable()
        # once the catchup flows, the peon converges; the NEXT lease
        # round (the leader ticks them continuously) restores reads
        net.mons[0].paxos._extend_lease_locked()
        net.pump()
        assert net.mons[2].committed == [(1, b"a")]
        net.mons[0].paxos._extend_lease_locked()
        net.pump()
        assert stale.is_readable()


class TestTrim:
    def _trimmy(self, net):
        for m in net.mons:
            m.paxos.TRIM_MIN = 5
            m.paxos.TRIM_TOLERANCE = 10
            m.get_full_state = lambda m=m: __import__(
                "ceph_tpu.encoding", fromlist=["x"]).encode_any(
                    m.committed)
            def set_full(blob, m=m):
                m.committed = __import__(
                    "ceph_tpu.encoding", fromlist=["x"]).decode_any(blob)
                return True
            m.set_full_state = set_full

    def test_store_stays_bounded(self):
        net = Net(3)
        self._trimmy(net)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        for i in range(40):
            net.mons[0].paxos.propose(b"v%d" % i)
            net.pump()
        lead = net.mons[0].paxos
        assert lead.last_committed == 40
        assert lead.first_committed >= 25
        live = [k for k, _ in net.mons[0].store.get_iterator("paxos")
                if k[0] == "0"]
        assert len(live) <= lead.TRIM_TOLERANCE + 2
        # trimmed versions really left the store
        assert net.mons[0].store.get("paxos", "%016d" % 1) is None

    def test_laggard_peon_full_syncs(self):
        """A peon away past the trim horizon converges through the
        full-state sync instead of wedging on missing increments."""
        net = Net(3)
        self._trimmy(net)
        net.make_leader(0, [0, 1, 2])
        net.pump()
        net.mons[0].paxos.propose(b"seed")
        net.pump()
        net.down.add(2)
        net.make_leader(0, [0, 1])
        net.pump()
        for i in range(30):                # way past TRIM_TOLERANCE
            net.mons[0].paxos.propose(b"x%d" % i)
            net.pump()
        assert net.mons[0].paxos.first_committed > 2
        net.down.clear()
        net.make_leader(0, [0, 1, 2])
        net.pump()
        p2 = net.mons[2].paxos
        assert p2.last_committed == net.mons[0].paxos.last_committed
        # service state adopted wholesale (the hook swapped .committed)
        assert net.mons[2].committed == net.mons[0].committed
