"""PG scrub tests: detect and repair replica divergence.

Models the reference's scrub/repair behavior (PrimaryLogPG scrub,
osd_scrub_auto_repair): the primary collects per-object
(version, crc, size) from every acting replica, flags mismatches, and
pushes the authoritative copy.
"""

import time

import pytest

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


@pytest.fixture(scope="module")
def ctx():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    client = cluster.client()
    cluster.create_replicated_pool(client, "scrubbed", size=3, pg_num=4)
    ioctx = client.open_ioctx("scrubbed")
    yield cluster, client, ioctx
    cluster.stop()


def primary_and_replicas(cluster, client, pool_name, oid):
    m = client.osdmap
    pool_id = client.pool_id(pool_name)
    pool = m.pools[pool_id]
    pgid = pool.raw_pg_to_pg(m.object_to_pg(pool_id, oid))
    _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
    return pgid, primary, [o for o in acting if o != primary]


def run_scrub(cluster, osd_id, pgid, timeout=10.0):
    osd = cluster.osds[osd_id]
    assert osd.scrub_pg(pgid)
    pg = osd.pgs[pgid]
    assert wait_until(
        lambda: pg.scrub_stats.get("state") in ("clean", "inconsistent"),
        timeout), pg.scrub_stats
    return pg.scrub_stats


class TestScrub:
    def test_clean_scrub(self, ctx):
        cluster, client, ioctx = ctx
        ioctx.write_full("clean-obj", b"consistent" * 100)
        pgid, primary, _ = primary_and_replicas(
            cluster, client, "scrubbed", "clean-obj")
        stats = run_scrub(cluster, primary, pgid)
        assert stats["state"] == "clean"
        assert stats["errors"] == 0

    def test_detects_and_repairs_bitrot(self, ctx):
        cluster, client, ioctx = ctx
        payload = b"pristine data " * 200
        ioctx.write_full("rot-obj", payload)
        pgid, primary, replicas = primary_and_replicas(
            cluster, client, "scrubbed", "rot-obj")
        # corrupt one replica's copy behind the cluster's back
        victim = cluster.osds[replicas[0]]
        cid = ("pg", str(pgid), -1)
        from ceph_tpu.store.object_store import Transaction
        txn = Transaction()
        txn.write(cid, "rot-obj", 0, b"ROTTEN")
        victim.store.queue_transaction(txn)
        assert victim.store.read(cid, "rot-obj")[:6] == b"ROTTEN"
        stats = run_scrub(cluster, primary, pgid)
        assert stats["errors"] >= 1
        assert stats["repaired"] >= 1
        # the repair pushed the authoritative bytes back
        assert wait_until(
            lambda: victim.store.read(cid, "rot-obj")[:6] != b"ROTTEN",
            10)
        assert victim.store.read(cid, "rot-obj")[:len(payload)] == payload
        # a second scrub is clean again
        stats = run_scrub(cluster, primary, pgid)
        assert stats["state"] == "clean"

    def test_deep_scrub_ec_repairs_corrupt_data_shard(self, ctx):
        """Deep scrub on an EC pool verifies every shard against the
        write-time hinfo crcs and rebuilds a corrupt shard from the
        survivors. The adversarial case: the corrupt shard is a DATA
        shard the normal read path would happily consume — the repair
        must restore it, never launder the corruption into the other
        shards."""
        import numpy as np

        cluster, client, _ = ctx
        cluster.create_ec_pool(client, "deepec",
                               {"plugin": "jerasure",
                                "technique": "reed_sol_van",
                                "k": "2", "m": "1"}, pg_num=4)
        ec_io = client.open_ioctx("deepec")
        payload = bytes(np.random.default_rng(5).integers(
            0, 256, 8192, dtype=np.uint8))
        ec_io.write_full("dobj", payload)
        m = client.osdmap
        pool_id = client.pool_id("deepec")
        pgid = m.pools[pool_id].raw_pg_to_pg(
            m.object_to_pg(pool_id, "dobj"))
        _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
        before = {
            s: cluster.osds[acting[s]].store.read(
                ("pg", str(pgid), s), "dobj") for s in range(3)}
        victim = cluster.osds[acting[1]]   # shard 1 = a data shard
        cid = ("pg", str(pgid), 1)
        from ceph_tpu.store.object_store import Transaction
        txn = Transaction()
        txn.write(cid, "dobj", 0,
                  bytes([b ^ 0xFF for b in before[1][:64]]))
        victim.store.queue_transaction(txn)
        # shallow scrub cannot see it (versions/sizes agree, and EC
        # shards legitimately differ byte-wise)
        osd = cluster.osds[primary]
        assert osd.scrub_pg(pgid)
        pg = osd.pgs[pgid]
        assert wait_until(lambda: pg.scrub_stats.get("state") in
                          ("clean", "inconsistent", "failed"), 10)
        assert pg.scrub_stats["errors"] == 0
        # deep scrub pinpoints the corrupt shard via hinfo and rebuilds
        # it from the other shards
        assert osd.scrub_pg(pgid, deep=True)
        assert wait_until(lambda: pg.scrub_stats.get("deep") and
                          pg.scrub_stats.get("state") in
                          ("clean", "inconsistent"), 20), pg.scrub_stats
        assert pg.scrub_stats["errors"] == 1
        assert pg.scrub_stats["repaired"] == 1
        assert wait_until(
            lambda: all(
                cluster.osds[acting[s]].store.read(
                    ("pg", str(pgid), s), "dobj") == before[s]
                for s in range(3)), 10)
        assert ec_io.read("dobj") == payload

    def test_detects_missing_replica_copy(self, ctx):
        cluster, client, ioctx = ctx
        ioctx.write_full("gone-obj", b"here" * 50)
        pgid, primary, replicas = primary_and_replicas(
            cluster, client, "scrubbed", "gone-obj")
        victim = cluster.osds[replicas[0]]
        cid = ("pg", str(pgid), -1)
        from ceph_tpu.store.object_store import Transaction
        txn = Transaction()
        txn.remove(cid, "gone-obj")
        victim.store.queue_transaction(txn)
        stats = run_scrub(cluster, primary, pgid)
        assert stats["errors"] >= 1 and stats["repaired"] >= 1
        assert wait_until(
            lambda: victim.store.exists(cid, "gone-obj"), 10)
