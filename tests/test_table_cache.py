"""Decode-table cache + single-erasure XOR fast path.

Models the reference's ISA table-cache behavior
(src/erasure-code/isa/ErasureCodeIsaTableCache.{h,cc}: LRU of decode
tables keyed by erasure signature) and the single-erasure region-XOR
shortcut (src/erasure-code/isa/xor_op.{h,cc}).
"""

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.models.table_cache import TableCache, xor_parity_rows


def make(plugin, **profile):
    prof = {str(k): str(v) for k, v in profile.items()}
    return registry.factory(plugin, prof)


def payload(size, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=size, dtype=np.uint8).tobytes()


class TestTableCache:
    def test_lru_eviction_and_stats(self):
        c = TableCache(capacity=2)
        c.put(("a",), {"v": 1})
        c.put(("b",), {"v": 2})
        assert c.get(("a",)) == {"v": 1}     # refresh a
        c.put(("c",), {"v": 3})              # evicts b (LRU)
        assert c.get(("b",)) is None
        assert c.get(("a",)) is not None
        assert c.get(("c",)) is not None
        s = c.stats()
        assert s["entries"] == 2 and s["evictions"] == 1
        assert s["hits"] == 3 and s["misses"] == 1

    def test_put_race_first_writer_wins(self):
        c = TableCache()
        first = c.put(("s",), {"v": 1})
        second = c.put(("s",), {"v": 2})
        assert first is second and second["v"] == 1

    def test_bad_capacity(self):
        with pytest.raises(ValueError):
            TableCache(capacity=0)


class TestCodecCacheIntegration:
    def test_repeated_signature_hits_cache(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        raw = payload(4096)
        encoded = codec.encode(set(range(6)), raw)
        for _ in range(3):
            chunks = {i: encoded[i] for i in range(6) if i not in (0, 1)}
            decoded = codec.decode({0, 1}, chunks)
            assert np.array_equal(decoded[0], encoded[0])
        stats = codec.table_cache_stats()
        assert stats["misses"] == 1 and stats["hits"] == 2

    def test_prepare_clears_cache(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        raw = payload(4096)
        encoded = codec.encode(set(range(6)), raw)
        chunks = {i: encoded[i] for i in range(6) if i not in (0, 1)}
        codec.decode({0, 1}, chunks)
        codec.prepare()
        assert codec.table_cache_stats()["entries"] == 0


class TestXorFastPath:
    @pytest.mark.parametrize("technique,kw", [
        ("reed_sol_van", dict(k=4, m=2, w=8)),
        ("liberation", dict(k=3, m=2, w=7)),
        ("blaum_roth", dict(k=4, m=2, w=6)),
        ("liber8tion", dict(k=4, m=2, w=8)),
        ("cauchy_good", dict(k=4, m=2, w=8)),
    ])
    def test_single_data_erasure_uses_xor(self, technique, kw):
        codec = make("jerasure", technique=technique, **kw)
        assert codec._xor_rows, technique  # first parity is a plain XOR
        raw = payload(8192, seed=3)
        n = codec.get_chunk_count()
        encoded = codec.encode(set(range(n)), raw)
        chunks = {i: encoded[i] for i in range(n) if i != 2}
        decoded = codec.decode({2}, chunks)
        assert np.array_equal(decoded[2], encoded[2])
        assert codec.xor_fast_hits == 1
        assert codec.table_cache_stats()["misses"] == 0  # never hit the cache

    def test_xor_parity_erasure_uses_xor(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        raw = payload(4096, seed=5)
        encoded = codec.encode(set(range(6)), raw)
        chunks = {i: encoded[i] for i in range(6) if i != 4}  # parity row 0
        decoded = codec.decode({4}, chunks)
        assert np.array_equal(decoded[4], encoded[4])
        assert codec.xor_fast_hits == 1

    def test_non_xor_parity_falls_back(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        raw = payload(4096, seed=7)
        encoded = codec.encode(set(range(6)), raw)
        chunks = {i: encoded[i] for i in range(6) if i != 5}  # parity row 1
        decoded = codec.decode({5}, chunks)
        assert np.array_equal(decoded[5], encoded[5])
        assert codec.xor_fast_hits == 0

    def test_double_erasure_falls_back(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        raw = payload(4096, seed=9)
        encoded = codec.encode(set(range(6)), raw)
        chunks = {i: encoded[i] for i in range(6) if i not in (1, 3)}
        decoded = codec.decode({1, 3}, chunks)
        assert np.array_equal(decoded[1], encoded[1])
        assert np.array_equal(decoded[3], encoded[3])
        assert codec.xor_fast_hits == 0

    def test_xor_rows_detection(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=3, w=8)
        rows = xor_parity_rows(codec._bitmat, codec.k, codec.w)
        assert rows == [0]  # Vandermonde: only the first parity is all-ones

    def test_minimum_to_decode_prefers_xor_group(self):
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        # shard 0 lost; the XOR group {1,2,3,P0=4} beats {1,2,3,5}
        assert codec.minimum_to_decode({0}, {1, 2, 3, 4, 5}) == {1, 2, 3, 4}
        # XOR parity unavailable too -> greedy fallback
        assert codec.minimum_to_decode({0}, {1, 2, 3, 5}) == {1, 2, 3, 5}

    def test_osd_read_path_uses_xor(self):
        """The ECBackend degraded-read flow (minimum_to_decode -> fetch ->
        ec_util.decode) must hit the batched XOR shortcut, not the matrix
        path."""
        from ceph_tpu.osd import ec_util
        codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
        sinfo = ec_util.StripeInfo(4, 4 * 64)
        payload = bytes(np.random.default_rng(11).integers(
            0, 256, size=3 * sinfo.stripe_width, dtype=np.uint8))
        shards = ec_util.encode(sinfo, codec, payload)
        want = {0, 1, 2, 3}          # all data shards (a normal read)
        avail = set(shards) - {2}    # one data shard's OSD is down
        to_read = codec.minimum_to_decode(want, avail)
        assert to_read == {0, 1, 3, 4}
        fetched = {s: shards[s] for s in to_read}
        assert ec_util.decode_concat(sinfo, codec, fetched)[:len(payload)] \
            == payload
        assert codec.xor_fast_hits == 1
        assert codec.table_cache_stats()["misses"] == 0


class TestDecodeBank:
    """The device-resident decode-matrix bank: every C(n,k) signature's
    bitmatrix precomputed and uploaded in one transfer, so a fresh
    erasure signature costs a device slice, not a host build + H2D."""

    def test_bank_builds_and_matches_per_entry(self):
        import itertools
        codec = make("jax_tpu", technique="reed_sol_van", k=4, m=2, w=8)
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, parity], axis=1)
        # every signature decodes bit-exact through the bank
        for avail in itertools.combinations(range(6), 4):
            out = np.asarray(codec.decode_batch(
                avail, full[:, list(avail), :]))
            assert np.array_equal(out, full), avail
        assert codec._bank_state == "built"
        assert len(codec._bank_index) == 15   # C(6,4)

    def test_bank_infeasible_falls_back(self):
        codec = make("jax_tpu", technique="reed_sol_van", k=4, m=2, w=8)
        codec.DECODE_BANK_LIMIT = 1           # force infeasible
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, size=(1, 4, 512), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, parity], axis=1)
        avail = (0, 2, 3, 5)
        out = np.asarray(codec.decode_batch(
            avail, full[:, list(avail), :]))
        assert np.array_equal(out, full)
        assert codec._bank_state == "infeasible"

    def test_numpy_backend_never_builds_bank(self):
        codec = make("jerasure", technique="reed_sol_van", k=3, m=2, w=8)
        rng = np.random.default_rng(5)
        data = rng.integers(0, 256, size=(1, 3, 256), dtype=np.uint8)
        parity = np.asarray(codec.encode_batch(data))
        full = np.concatenate([data, parity], axis=1)
        avail = (1, 2, 4)
        out = np.asarray(codec.decode_batch(
            avail, full[:, list(avail), :]))
        assert np.array_equal(out, full)
        assert codec._bank_state == "infeasible"
