"""Reed-Solomon codec tests: roundtrip, bit-exactness, interface semantics.

Modeled on the reference's typed technique tests
(src/test/erasure-code/TestErasureCodeJerasure.cc): encode/decode with
content verification of every reconstructed chunk, minimum_to_decode,
alignment variants, sanity_check_k.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.models.base import ErasureCodeError
from ceph_tpu.ops import gf_ref


def make(plugin, **profile):
    prof = {str(k): str(v) for k, v in profile.items()}
    return registry.factory(plugin, prof)


def payload(size, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=size, dtype=np.uint8).tobytes()


@pytest.mark.parametrize("backend_plugin", ["jerasure", "jax_tpu"])
@pytest.mark.parametrize("technique", ["reed_sol_van", "reed_sol_r6_op"])
@pytest.mark.parametrize("w", [8, 16])
def test_roundtrip_all_erasures(backend_plugin, technique, w):
    k, m = 4, 2
    codec = make(backend_plugin, technique=technique, k=k, m=m, w=w)
    assert codec.get_chunk_count() == k + m
    raw = payload(1013)  # deliberately unaligned
    want = set(range(k + m))
    encoded = codec.encode(want, raw)
    assert set(encoded) == want
    blocksize = codec.get_chunk_size(len(raw))
    assert all(c.size == blocksize for c in encoded.values())
    # systematic prefix equals input
    concat = b"".join(encoded[i].tobytes() for i in range(k))
    assert concat[:len(raw)] == raw

    for n_erase in range(1, m + 1):
        for gone in itertools.combinations(range(k + m), n_erase):
            chunks = {i: encoded[i] for i in want if i not in gone}
            decoded = codec.decode(set(gone), chunks)
            for i in gone:
                assert np.array_equal(decoded[i], encoded[i]), \
                    (technique, w, gone, i)


@pytest.mark.parametrize("technique,w,k,m", [
    ("reed_sol_van", 8, 8, 3),
    ("reed_sol_van", 32, 4, 2),
    ("reed_sol_r6_op", 8, 6, 2),
])
def test_jax_matches_numpy_bit_exact(technique, w, k, m):
    cpu = make("jerasure", technique=technique, k=k, m=m, w=w)
    tpu = make("jax_tpu", technique=technique, k=k, m=m, w=w)
    assert np.array_equal(cpu.coding, tpu.coding)
    rng = np.random.default_rng(3)
    n = cpu.get_chunk_size(k * 4096)
    data = rng.integers(0, 256, size=(2, k, n), dtype=np.uint8)
    assert np.array_equal(cpu.encode_batch(data), tpu.encode_batch(data))
    avail = tuple(sorted(rng.choice(k + m, size=k, replace=False).tolist()))
    chunks = rng.integers(0, 256, size=(2, k, n), dtype=np.uint8)
    assert np.array_equal(cpu.decode_batch(avail, chunks),
                          tpu.decode_batch(avail, chunks))


def test_matches_reference_oracle():
    k, m, w = 8, 3, 8
    codec = make("jax_tpu", technique="reed_sol_van", k=k, m=m, w=w)
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, size=(k, 2048), dtype=np.uint8)
    parity = codec.encode_batch(data[None])[0]
    ref = gf_ref.matrix_encode_ref(codec.coding, data, w)
    assert np.array_equal(parity, ref)


def test_chunk_size_semantics():
    codec = make("jerasure", technique="reed_sol_van", k=8, m=3, w=8)
    # alignment = k*w*4 = 256 (ErasureCodeJerasure.cc:168-178)
    assert codec.get_alignment() == 256
    assert codec.get_chunk_size(1048576) == 131072
    assert codec.get_chunk_size(1) == 32
    assert codec.get_chunk_size(257) == 64
    per = make("jerasure", technique="reed_sol_van", k=8, m=3, w=8,
               **{"jerasure-per-chunk-alignment": "true"})
    assert per.get_alignment() == 128
    assert per.get_chunk_size(1048576) == 131072
    assert per.get_chunk_size(1000) == 128


def test_minimum_to_decode():
    codec = make("jerasure", technique="reed_sol_van", k=4, m=2, w=8)
    # want subset of available -> want itself
    assert codec.minimum_to_decode({1, 2}, {0, 1, 2, 3}) == {1, 2}
    # otherwise first k available
    assert codec.minimum_to_decode({0}, {1, 2, 3, 4, 5}) == {1, 2, 3, 4}
    with pytest.raises(ErasureCodeError):
        codec.minimum_to_decode({0}, {1, 2, 3})
    # cost-aware variant reduces to the same selection with equal costs
    assert codec.minimum_to_decode_with_cost(
        {0}, {i: 1 for i in (1, 2, 3, 4, 5)}) == {1, 2, 3, 4}


def test_sanity_check_k():
    with pytest.raises(ErasureCodeError):
        make("jerasure", technique="reed_sol_van", k=1, m=2, w=8)


def test_bad_w_rejected():
    with pytest.raises(ErasureCodeError):
        make("jerasure", technique="reed_sol_van", k=4, m=2, w=11)


def test_raid6_forces_m2():
    codec = make("jerasure", technique="reed_sol_r6_op", k=4, m=7, w=8)
    assert codec.get_coding_chunk_count() == 2
    assert codec.get_profile()["m"] == "2"


def test_chunk_mapping_remap():
    # mapping: first position coding, then data (like the interface doc's
    # remap example, ErasureCodeInterface.h:402-434)
    codec = make("jerasure", technique="reed_sol_van", k=2, m=1, w=8,
                 mapping="_DD")
    assert codec.get_chunk_mapping() == [1, 2, 0]
    raw = payload(640)
    encoded = codec.encode({0, 1, 2}, raw)
    blocksize = codec.get_chunk_size(len(raw))
    # data lands at positions 1 and 2
    assert encoded[1].tobytes() == raw[:blocksize]
    assert np.array_equal(
        encoded[2][:len(raw) - blocksize],
        np.frombuffer(raw[blocksize:], dtype=np.uint8))
    # decode_concat recovers the original through the remap
    assert codec.decode_concat(encoded)[:len(raw)] == raw
    # erase a remapped chunk and reconstruct it
    chunks = {i: encoded[i] for i in (0, 2)}
    decoded = codec.decode({1}, chunks)
    assert np.array_equal(decoded[1], encoded[1])


def test_decode_concat_roundtrip():
    codec = make("jax_tpu", technique="reed_sol_van", k=5, m=2, w=8)
    raw = payload(3333)
    encoded = codec.encode(set(range(7)), raw)
    del encoded[0], encoded[4]
    assert codec.decode_concat(encoded)[:len(raw)] == raw
