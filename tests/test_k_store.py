"""KStore: the everything-in-kv ObjectStore (src/os/kstore role)."""

from __future__ import annotations

import random

import pytest

from ceph_tpu.store.k_store import KStore
from ceph_tpu.store.mem_store import MemStore
from ceph_tpu.store.object_store import Transaction

from .test_block_store import TestDropIn as BlockDropIn


def make_store(path, **kw):
    kw.setdefault("kv_sync", False)
    st = KStore(str(path), **kw)
    st.mount()
    return st


class TestBasics:
    def test_roundtrip_and_persistence(self, tmp_path):
        st = make_store(tmp_path, stripe_size=4096)
        t = Transaction()
        t.create_collection("c")
        t.write("c", "o", 0, b"v" * 10000)      # spans stripes
        t.write("c", "o", 5000, b"patch")
        t.setattr("c", "o", "a", b"x")
        t.omap_setkeys("c", "o", {"k": b"v"})
        st.queue_transaction(t)
        want = bytearray(b"v" * 10000)
        want[5000:5005] = b"patch"
        assert st.read("c", "o") == bytes(want)
        st.umount()

        st2 = make_store(tmp_path, stripe_size=4096)
        assert st2.read("c", "o") == bytes(want)
        assert st2.getattr("c", "o", "a") == b"x"
        assert st2.omap_get("c", "o") == {"k": b"v"}
        st2.umount()

    def test_truncate_across_stripes(self, tmp_path):
        st = make_store(tmp_path, stripe_size=1024)
        t = Transaction()
        t.create_collection("c")
        t.write("c", "o", 0, b"z" * 5000)
        t.truncate("c", "o", 1500)
        st.queue_transaction(t)
        assert st.read("c", "o") == b"z" * 1500
        t = Transaction()
        t.truncate("c", "o", 3000)      # re-extend reads zeros
        st.queue_transaction(t)
        assert st.read("c", "o") == b"z" * 1500 + b"\0" * 1500
        st.umount()


class TestDropIn(BlockDropIn):
    """The same randomized differential-vs-MemStore proof the
    BlockStore passes, re-run against KStore."""

    def test_differential_vs_memstore(self, tmp_path):
        rng = random.Random(13)
        mem = MemStore()
        mem.mount()
        blk = make_store(tmp_path, stripe_size=8192)
        t = Transaction()
        t.create_collection("c")
        mem.queue_transaction(t)
        t = Transaction()
        t.create_collection("c")
        blk.queue_transaction(t)
        for round_no in range(25):
            ops = self._random_ops(rng, rng.randrange(1, 4))
            for store in (mem, blk):
                for op in ops:
                    t = Transaction()
                    t.ops = [op]
                    try:
                        store.queue_transaction(t)
                    except KeyError:
                        pass
            assert mem.list_objects("c") == blk.list_objects("c"), \
                "round %d" % round_no
            for oid in mem.list_objects("c"):
                assert mem.read("c", oid) == blk.read("c", oid), \
                    (round_no, oid)
                assert mem.omap_get("c", oid) == blk.omap_get("c", oid)
        blk.umount()

    def test_missing_object_ops_raise_like_memstore(self, tmp_path):
        mem = MemStore()
        mem.mount()
        blk = make_store(tmp_path)
        for store in (mem, blk):
            t = Transaction()
            t.create_collection("c")
            store.queue_transaction(t)
        for op in [("clone", "c", "ghost", "x"),
                   ("rmattr", "c", "ghost", "a"),
                   ("omap_rmkeys", "c", "ghost", ["k"]),
                   ("move_rename", "c", "ghost", "c", "y")]:
            for store in (mem, blk):
                t = Transaction()
                t.ops = [op]
                with pytest.raises(KeyError):
                    store.queue_transaction(t)
        blk.umount()


class TestIntraTxnOmap:
    def test_same_txn_omap_then_clone(self, tmp_path):
        """Omap keys written earlier in a transaction are visible to a
        clone later in the same transaction (the M-namespace overlay)."""
        st = make_store(tmp_path)
        t = Transaction()
        t.create_collection("c")
        t.touch("c", "src")
        t.omap_setkeys("c", "src", {"k": b"v"})
        t.clone("c", "src", "dst")
        st.queue_transaction(t)
        assert st.omap_get("c", "dst") == {"k": b"v"}
        st.umount()

    def test_same_txn_rmkeys_then_clone(self, tmp_path):
        st = make_store(tmp_path)
        t = Transaction()
        t.create_collection("c")
        t.touch("c", "src")
        t.omap_setkeys("c", "src", {"a": b"1", "b": b"2"})
        st.queue_transaction(t)
        t = Transaction()
        t.omap_rmkeys("c", "src", ["a"])
        t.clone("c", "src", "dst")
        st.queue_transaction(t)
        assert st.omap_get("c", "dst") == {"b": b"2"}
        st.umount()

    def test_same_txn_setkeys_then_remove_no_orphans(self, tmp_path):
        st = make_store(tmp_path)
        t = Transaction()
        t.create_collection("c")
        t.touch("c", "o")
        t.omap_setkeys("c", "o", {"ghost": b"x"})
        t.remove("c", "o")
        st.queue_transaction(t)
        # recreate: the orphan key must not reattach
        t = Transaction()
        t.touch("c", "o")
        st.queue_transaction(t)
        assert st.omap_get("c", "o") == {}
        st.umount()
