"""HBM-resident chunk tier: encode -> scrub -> reconstruct without
re-crossing the host-device pipe.

Checks the tier's contract against numpy oracles: parity matches the
reference encode, device digests match the host digest twin, rebuilt
shards are bit-exact, and the LRU bound holds.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.osd.hbm_tier import HbmChunkTier, host_digest

K, M = 4, 2
OBJ = 64 * 1024


@pytest.fixture(scope="module")
def codec():
    return registry.factory("jax_tpu", {
        "technique": "reed_sol_van", "k": str(K), "m": str(M),
        "w": "8"})


@pytest.fixture(scope="module")
def ref_codec():
    return registry.factory("jerasure", {
        "technique": "reed_sol_van", "k": str(K), "m": str(M),
        "w": "8"})


def make_batch(codec, nobjs, seed=0):
    n = codec.get_chunk_size(OBJ)
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(nobjs, K, n), dtype=np.uint8)


class TestHbmTier:
    def test_encode_retains_and_matches_reference(self, codec,
                                                  ref_codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 4)
        names = ["o%d" % i for i in range(4)]
        parity = np.asarray(tier.put_encode(names, data))
        want = np.asarray(ref_codec.encode_batch(data))
        assert np.array_equal(parity, want)
        assert all(tier.resident(n) for n in names)
        # the resident copy is the full chunk set
        full = np.asarray(tier.get("o2"))
        assert np.array_equal(full[:K], data[2])
        assert np.array_equal(full[K:], want[2])

    def test_deep_scrub_digests(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 3, seed=1)
        names = ["s%d" % i for i in range(3)]
        tier.put_encode(names, data)
        digs = tier.deep_scrub(names)
        for i, name in enumerate(names):
            full = np.asarray(tier.get(name))
            assert np.array_equal(digs[name], host_digest(full)), name
        # position sensitivity: swapping two bytes changes the digest
        mut = np.asarray(tier.get("s0")).copy()
        mut[0, 0], mut[0, 1] = mut[0, 1], mut[0, 0]
        if mut[0, 0] != mut[0, 1]:
            assert host_digest(mut)[0] != digs["s0"][0]

    def test_reconstruct_lost_shards(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 2, seed=2)
        tier.put_encode(["r0", "r1"], data)
        full = np.asarray(tier.get("r1"))
        for lost in ((0,), (K,), (1, K + 1)):
            rebuilt = np.asarray(tier.reconstruct("r1", lost))
            for j, shard in enumerate(lost):
                assert np.array_equal(rebuilt[j], full[shard]), \
                    "shard %d mismatch" % shard

    def test_reconstruct_batch_fused(self, codec):
        """One fused program rebuilds a different lost shard per
        object, bit-exact."""
        tier = HbmChunkTier(codec)
        nobjs = 6
        data = make_batch(codec, nobjs, seed=5)
        names = ["b%d" % i for i in range(nobjs)]
        tier.put_encode(names, data)
        lost = [(i * 2 + 1) % (K + M) for i in range(nobjs)]
        rebuilt = np.asarray(tier.reconstruct_batch(names, lost))
        for i, name in enumerate(names):
            full = np.asarray(tier.get(name))
            assert np.array_equal(rebuilt[i], full[lost[i]]), \
                "object %d shard %d" % (i, lost[i])

    def test_lru_eviction(self, codec):
        tier = HbmChunkTier(codec, capacity_objects=3)
        data = make_batch(codec, 5, seed=3)
        tier.put_encode(["e%d" % i for i in range(5)], data)
        assert tier.stats()["resident_objects"] == 3
        assert not tier.resident("e0") and not tier.resident("e1")
        assert tier.resident("e4")
        with pytest.raises(KeyError):
            tier.reconstruct("e0", (0,))

    def test_drop(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 1, seed=4)
        tier.put_encode(["d0"], data)
        tier.drop("d0")
        assert not tier.resident("d0")
        assert tier.stats()["resident_objects"] == 0
