"""HBM-resident chunk tier: encode -> scrub -> reconstruct without
re-crossing the host-device pipe.

Checks the tier's contract against numpy oracles: parity matches the
reference encode, device digests match the host digest twin, rebuilt
shards are bit-exact, and the LRU bound holds.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.osd.hbm_tier import HbmChunkTier, host_digest

K, M = 4, 2
OBJ = 64 * 1024


@pytest.fixture(scope="module")
def codec():
    return registry.factory("jax_tpu", {
        "technique": "reed_sol_van", "k": str(K), "m": str(M),
        "w": "8"})


@pytest.fixture(scope="module")
def ref_codec():
    return registry.factory("jerasure", {
        "technique": "reed_sol_van", "k": str(K), "m": str(M),
        "w": "8"})


def make_batch(codec, nobjs, seed=0):
    n = codec.get_chunk_size(OBJ)
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(nobjs, K, n), dtype=np.uint8)


class TestHbmTier:
    def test_encode_retains_and_matches_reference(self, codec,
                                                  ref_codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 4)
        names = ["o%d" % i for i in range(4)]
        parity = np.asarray(tier.put_encode(names, data))
        want = np.asarray(ref_codec.encode_batch(data))
        assert np.array_equal(parity, want)
        assert all(tier.resident(n) for n in names)
        # the resident copy is the full chunk set
        full = np.asarray(tier.get("o2"))
        assert np.array_equal(full[:K], data[2])
        assert np.array_equal(full[K:], want[2])

    def test_deep_scrub_digests(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 3, seed=1)
        names = ["s%d" % i for i in range(3)]
        tier.put_encode(names, data)
        digs = tier.deep_scrub(names)
        for i, name in enumerate(names):
            full = np.asarray(tier.get(name))
            assert np.array_equal(digs[name], host_digest(full)), name
        # position sensitivity: swapping two bytes changes the digest
        mut = np.asarray(tier.get("s0")).copy()
        mut[0, 0], mut[0, 1] = mut[0, 1], mut[0, 0]
        if mut[0, 0] != mut[0, 1]:
            assert host_digest(mut)[0] != digs["s0"][0]

    def test_reconstruct_lost_shards(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 2, seed=2)
        tier.put_encode(["r0", "r1"], data)
        full = np.asarray(tier.get("r1"))
        for lost in ((0,), (K,), (1, K + 1)):
            rebuilt = np.asarray(tier.reconstruct("r1", lost))
            for j, shard in enumerate(lost):
                assert np.array_equal(rebuilt[j], full[shard]), \
                    "shard %d mismatch" % shard

    def test_reconstruct_batch_fused(self, codec):
        """One fused program rebuilds a different lost shard per
        object, bit-exact."""
        tier = HbmChunkTier(codec)
        nobjs = 6
        data = make_batch(codec, nobjs, seed=5)
        names = ["b%d" % i for i in range(nobjs)]
        tier.put_encode(names, data)
        lost = [(i * 2 + 1) % (K + M) for i in range(nobjs)]
        rebuilt = np.asarray(tier.reconstruct_batch(names, lost))
        for i, name in enumerate(names):
            full = np.asarray(tier.get(name))
            assert np.array_equal(rebuilt[i], full[lost[i]]), \
                "object %d shard %d" % (i, lost[i])

    def test_lru_eviction(self, codec):
        tier = HbmChunkTier(codec, capacity_objects=3)
        data = make_batch(codec, 5, seed=3)
        tier.put_encode(["e%d" % i for i in range(5)], data)
        assert tier.stats()["resident_objects"] == 3
        assert not tier.resident("e0") and not tier.resident("e1")
        assert tier.resident("e4")
        with pytest.raises(KeyError):
            tier.reconstruct("e0", (0,))

    def test_drop(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 1, seed=4)
        tier.put_encode(["d0"], data)
        tier.drop("d0")
        assert not tier.resident("d0")
        assert tier.stats()["resident_objects"] == 0


def _ec_target(cluster, client, pool_name, oid):
    """(pgid, acting, primary) for an EC object."""
    m = client.osdmap
    pool_id = client.pool_id(pool_name)
    pgid = m.pools[pool_id].raw_pg_to_pg(m.object_to_pg(pool_id, oid))
    _, _, acting, primary = m.pg_to_up_acting_osds(pgid)
    return pgid, acting, primary


class TestAdoptAndInvalidate:
    """The dispatcher-pipeline adoption surface (adopt_encode) and the
    invalidation hooks the OSD wiring depends on."""

    def test_adopt_encode_matches_put_encode_layout(self, codec,
                                                    ref_codec):
        tier = HbmChunkTier(codec)
        n = codec.get_chunk_size(OBJ)
        stripes, chunk = 4, n // 4
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=(stripes, K, chunk),
                            dtype=np.uint8)
        parity = np.asarray(ref_codec.encode_batch(data))
        tier.adopt_encode(("pg1", "a0"), data, parity, codec)
        assert tier.resident(("pg1", "a0"))
        full = np.asarray(tier.get(("pg1", "a0")))
        # row i == shard i's whole chunk stream (stripe-interleaved)
        want_data = np.ascontiguousarray(
            data.transpose(1, 0, 2)).reshape(K, -1)
        want_par = np.ascontiguousarray(
            parity.transpose(1, 0, 2)).reshape(M, -1)
        assert np.array_equal(full[:K], want_data)
        assert np.array_equal(full[K:], want_par)
        # and the consumers work on the adopted entry
        rebuilt = np.asarray(tier.reconstruct(("pg1", "a0"), (1,)))
        assert np.array_equal(rebuilt[0], full[1])
        assert tier.stats()["adopted"] == 1

    def test_drop_prefix_invalidates_one_pg(self, codec):
        tier = HbmChunkTier(codec)
        data = make_batch(codec, 2, seed=8)
        tier.put_encode([("pgA", "x"), ("pgB", "y")], data)
        assert tier.drop_prefix("pgA") == 1
        assert not tier.resident(("pgA", "x"))
        assert tier.resident(("pgB", "y"))

    def test_deep_scrub_groups_heterogeneous_shapes(self, codec):
        """One OSD-wide tier holds objects of different chunk sizes;
        deep_scrub fuses per shape and still returns every digest."""
        tier = HbmChunkTier(codec)
        d1 = make_batch(codec, 2, seed=9)
        tier.put_encode(["h0", "h1"], d1)
        n2 = codec.get_chunk_size(OBJ // 2)
        rng = np.random.default_rng(10)
        d2 = rng.integers(0, 256, size=(1, K, n2), dtype=np.uint8)
        tier.put_encode(["h2"], d2)
        digs = tier.deep_scrub(["h0", "h2", "h1"])
        for name in ("h0", "h1", "h2"):
            full = np.asarray(tier.get(name))
            assert np.array_equal(digs[name], host_digest(full)), name


FAST = {"osd_heartbeat_interval": 0.1,
        "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02}

EC_PROFILE = {"plugin": "jax_tpu", "technique": "reed_sol_van",
              "k": "2", "m": "1", "w": "8"}


class TestTierWiredIntoOsd:
    """ISSUE 7 tentpole (2): the tier serves the PRODUCTION data path.
    Whole-object EC writes are adopted device-side by the dispatcher
    pipeline; recovery reconstruction and scrub repair rebuild from the
    resident copy with zero extra h2d; eviction falls back to the
    survivor sub-read path; opt-in reads hit residency."""

    def _write_and_target(self, cluster, client, pool, oid, payload):
        ioctx = client.open_ioctx(pool)
        ioctx.write_full(oid, payload)
        pgid, acting, primary = _ec_target(cluster, client, pool, oid)
        return ioctx, pgid, acting, cluster.osds[primary]

    def test_recovery_reads_resident_copy_zero_extra_h2d(self):
        from .cluster_util import MiniCluster, wait_until
        import threading
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "hbmres", dict(EC_PROFILE),
                                   pg_num=4)
            payload = b"stay resident " * 512
            ioctx, pgid, acting, posd = self._write_and_target(
                cluster, client, "hbmres", "hobj", payload)
            key = (str(pgid), "hobj")
            assert posd.hbm_tier is not None
            # the write's encode was adopted by the pipeline
            assert wait_until(lambda: posd.hbm_tier.resident(key), 10)
            victim_shard = 1
            cid = ("pg", str(pgid), victim_shard)
            expected = cluster.osds[acting[victim_shard]].store.read(
                cid, "hobj")
            h2d_before = posd.tpu_dispatcher.perf.dump()[
                "l_tpu_h2d"]["avgcount"]
            hits_before = posd.hbm_tier.perf.get("l_hbm_hits")
            pg = posd.pgs[pgid]
            done = threading.Event()
            got = [None]

            def cb(data):
                got[0] = data
                done.set()

            pg.backend.recover_object("hobj", victim_shard, cb)
            assert done.wait(20)
            assert got[0] == expected
            # the reconstruction came from HBM residency: the
            # dispatcher shipped NOTHING host->device for it
            assert posd.tpu_dispatcher.perf.dump()[
                "l_tpu_h2d"]["avgcount"] == h2d_before
            assert posd.hbm_tier.perf.get("l_hbm_hits") > hits_before
        finally:
            cluster.stop()

    def test_eviction_falls_back_to_host_path(self):
        from .cluster_util import MiniCluster, wait_until
        import threading
        conf = dict(FAST)
        conf["osd_hbm_tier_capacity"] = 1
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "hbmev", dict(EC_PROFILE),
                                   pg_num=4)
            payload = b"evict me please " * 256
            ioctx, pgid, acting, posd = self._write_and_target(
                cluster, client, "hbmev", "evobj", payload)
            key = (str(pgid), "evobj")
            assert wait_until(lambda: posd.hbm_tier.resident(key), 10)
            # push the victim out of its primary's 1-entry tier
            for i in range(6):
                ioctx.write_full("filler-%d" % i, b"f" * 4096)
            assert wait_until(
                lambda: not posd.hbm_tier.resident(key), 10)
            misses_before = posd.hbm_tier.perf.get("l_hbm_misses")
            victim_shard = 0
            cid = ("pg", str(pgid), victim_shard)
            expected = cluster.osds[acting[victim_shard]].store.read(
                cid, "evobj")
            pg = posd.pgs[pgid]
            done = threading.Event()
            got = [None]

            def cb(data):
                got[0] = data
                done.set()

            # evicted -> the recovery falls back to the survivor
            # sub-read path and still rebuilds correctly
            pg.backend.recover_object("evobj", victim_shard, cb)
            assert done.wait(20)
            assert got[0] == expected
            assert posd.hbm_tier.perf.get("l_hbm_misses") \
                > misses_before
        finally:
            cluster.stop()

    def test_scrub_repair_rebuilds_from_residency(self):
        """Fault-injected shard corruption: deep scrub detects it from
        the stores, and the repair rebuild is served by the resident
        copy (zero dispatcher h2d for the reconstruction)."""
        from .cluster_util import MiniCluster, wait_until
        conf = dict(FAST)
        conf["osd_scrub_auto_repair"] = True
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "hbmscrub",
                                   dict(EC_PROFILE), pg_num=4)
            payload = b"scrub from hbm " * 512
            ioctx, pgid, acting, posd = self._write_and_target(
                cluster, client, "hbmscrub", "sobj", payload)
            key = (str(pgid), "sobj")
            assert wait_until(lambda: posd.hbm_tier.resident(key), 10)
            victim_shard = 0
            victim = cluster.osds[acting[victim_shard]]
            cid = ("pg", str(pgid), victim_shard)
            good = victim.store.read(cid, "sobj")
            # silent corruption behind the crc (store fault injection)
            victim.store.faults.mark_bitrot(cid, "sobj")
            h2d_before = posd.tpu_dispatcher.perf.dump()[
                "l_tpu_h2d"]["avgcount"]
            hits_before = posd.hbm_tier.perf.get("l_hbm_hits")
            assert posd.scrub_pg(pgid, deep=True)
            pg = posd.pgs[pgid]
            assert wait_until(
                lambda: pg.scrub_stats.get("deep")
                and pg.scrub_stats.get("state") in ("clean",
                                                    "inconsistent")
                and pg.scrub_stats.get("repaired", 0) >= 1, 30), \
                pg.scrub_stats
            assert wait_until(
                lambda: victim.store.read(cid, "sobj") == good, 20)
            # the rebuild hit residency, not the dispatcher
            assert posd.hbm_tier.perf.get("l_hbm_hits") > hits_before
            assert posd.tpu_dispatcher.perf.dump()[
                "l_tpu_h2d"]["avgcount"] == h2d_before
        finally:
            cluster.stop()

    def test_serve_reads_hits_residency_and_invalidates_on_write(self):
        from .cluster_util import MiniCluster, wait_until
        conf = dict(FAST)
        conf["osd_hbm_tier_serve_reads"] = True
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=conf).start()
        try:
            client = cluster.client()
            cluster.create_ec_pool(client, "hbmread", dict(EC_PROFILE),
                                   pg_num=4)
            # multi-stripe object: a partial overwrite then rewrites
            # ONE stripe, which must invalidate (a single-stripe
            # object would legitimately re-adopt — the overwrite
            # re-encodes the whole object)
            payload = b"read me from hbm " * 4096
            ioctx, pgid, acting, posd = self._write_and_target(
                cluster, client, "hbmread", "robj", payload)
            key = (str(pgid), "robj")
            assert wait_until(lambda: posd.hbm_tier.resident(key), 10)
            hits_before = posd.hbm_tier.perf.get("l_hbm_hits")
            assert ioctx.read("robj") == payload
            assert posd.hbm_tier.perf.get("l_hbm_hits") > hits_before
            # a partial overwrite INVALIDATES the entry (stale
            # residency must never serve) and the read still works
            ioctx.write("robj", b"XY", 4)
            assert not posd.hbm_tier.resident(key)
            want = bytearray(payload)
            want[4:6] = b"XY"
            assert ioctx.read("robj") == bytes(want)
        finally:
            cluster.stop()


class TestAsokStatus:
    def test_hbm_and_dispatch_status_commands(self, tmp_path):
        """Satellite: `hbm status` / `dispatch status` asok dumps show
        ring occupancy and residency hit rates."""
        from ceph_tpu.common import Context
        from ceph_tpu.osd.osd_daemon import OSDDaemon
        ctx = Context(name="osd.77")
        ctx.init_admin_socket(str(tmp_path / "osd77.asok"))
        osd = OSDDaemon(77, {0: ("127.0.0.1", 6789)}, ctx=ctx)
        try:
            st = ctx.admin_socket.execute("hbm status")
            assert "resident_objects" in st
            assert "hit_rate" in st and "evictions" in st
            ds = ctx.admin_socket.execute("dispatch status")
            assert ds["pipeline_depth"] >= 1
            assert set(ds["ring"]) == {"staging", "computing",
                                       "draining"}
            assert "coalesce_ratio" in ds and "segments_s" in ds
        finally:
            if osd.tpu_dispatcher is not None:
                osd.tpu_dispatcher.shutdown()
            osd.finisher.stop()
            ctx.shutdown()
