"""QoS op-queue tests: WPQ fairness, dmClock reservation/weight/limit.

Models the reference's queue unit tests
(src/test/common/test_weighted_priority_queue.cc,
src/test/dmclock/*): strict band ordering, proportional bandwidth by
priority/weight, reservation phase precedence, limit throttling, and
per-class FIFO preservation.
"""

import threading
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.osd.op_queue import (MClockOpClassQueue, QosShardedOpWQ,
                                   WeightedPriorityQueue, make_op_queue)


def drain(q, now=None, limit=10000):
    out = []
    for _ in range(limit):
        item = q.dequeue(now)
        if item is None:
            break
        out.append(item)
    return out


class TestWeightedPriorityQueue:
    def test_strict_outranks_normal(self):
        q = WeightedPriorityQueue()
        q.enqueue("client", 10, 0, "normal")
        q.enqueue_strict("peering", 200, "strict-hi")
        q.enqueue_strict("peering", 100, "strict-lo")
        assert drain(q) == ["strict-hi", "strict-lo", "normal"]

    def test_strict_fifo_within_priority(self):
        q = WeightedPriorityQueue()
        for i in range(5):
            q.enqueue_strict("x", 100, "s%d" % i)
        assert drain(q) == ["s%d" % i for i in range(5)]

    def test_fifo_within_bucket(self):
        q = WeightedPriorityQueue()
        for i in range(10):
            q.enqueue("client", 63, 0, i)
        assert drain(q) == list(range(10))

    def test_bandwidth_proportional_to_priority(self):
        q = WeightedPriorityQueue()
        n = 600
        for i in range(n):
            q.enqueue("client", 60, 0, ("hi", i))
            q.enqueue("recovery", 3, 0, ("lo", i))
        first = drain(q, limit=210)
        hi = sum(1 for tag, _ in first if tag == "hi")
        lo = len(first) - hi
        # 60:3 weights -> the first slice should be overwhelmingly hi,
        # but lo must not starve
        assert hi > lo * 5
        assert lo >= 1
        # everything eventually drains
        assert len(first) + len(drain(q)) == 2 * n

    def test_cost_charges_deficit(self):
        q = WeightedPriorityQueue(min_cost=4096)
        for i in range(20):
            q.enqueue("client", 20, 1 << 20, ("big", i))   # 256 units each
            q.enqueue("recovery", 10, 0, ("small", i))     # 1 unit each
        out = drain(q, limit=30)
        # big ops have double the priority but 256x the cost, so the
        # cheap bucket must flow much faster despite lower priority:
        # nearly all smalls drain before the bigs start
        first_big = next(i for i, (tag, _) in enumerate(out)
                         if tag == "big")
        assert first_big >= 15
        assert len(out) == 30  # everything still drains

    def test_priority_zero_still_progresses(self):
        """priority<=0 must not deficit-starve (and with the shard lock
        held, a non-progressing bucket would wedge the whole shard)."""
        q = WeightedPriorityQueue()
        q.enqueue("recovery", 0, 0, "a")
        q.enqueue("recovery", 0, 1 << 20, "b")
        assert drain(q) == ["a", "b"]

    def test_len_and_empty(self):
        q = WeightedPriorityQueue()
        assert q.empty()
        q.enqueue("c", 1, 0, "a")
        q.enqueue_strict("c", 1, "b")
        assert len(q) == 2 and not q.empty()
        drain(q)
        assert q.empty()


class TestMClock:
    def test_reservation_served_first(self):
        q = MClockOpClassQueue({"client": (0.0, 1.0, 0.0),
                                "recovery": (1000.0, 1.0, 0.0)})
        t0 = time.monotonic()
        for i in range(4):
            q.enqueue("client", 63, 0, ("c", i))
            q.enqueue("recovery", 3, 0, ("r", i))
        # all recovery reservations tag <= now: they outrank weight-only
        out = drain(q, now=t0 + 1.0)
        assert [tag for tag, _ in out[:4]] == ["r"] * 4

    def test_weight_sharing_when_no_reservation(self):
        q = MClockOpClassQueue({"a": (0.0, 100.0, 0.0),
                                "b": (0.0, 1.0, 0.0)})
        for i in range(200):
            q.enqueue("a", 0, 0, ("a", i))
            q.enqueue("b", 0, 0, ("b", i))
        out = drain(q, now=time.monotonic() + 10, limit=100)
        a = sum(1 for tag, _ in out if tag == "a")
        assert a > 90  # ~100:1 weights

    def test_limit_throttles_class(self):
        q = MClockOpClassQueue({"recovery": (0.0, 1.0, 10.0)})
        t0 = time.monotonic()
        for i in range(5):
            q.enqueue("recovery", 0, 0, i)
        # at 10 ops/s only ~1-2 are eligible immediately after enqueue
        served_now = drain(q, now=t0)
        assert len(served_now) <= 2
        assert q.next_ready_in(t0) is not None
        # half a second later, ~5 more slots have accrued
        later = drain(q, now=t0 + 0.5)
        assert len(served_now) + len(later) == 5

    def test_byte_costs_do_not_invert_weights(self):
        """1MB client writes vs zero-cost recovery ops: with 500:1
        weights, client ops must keep dominating even though their byte
        cost is huge (cost normalizes to units, not seconds)."""
        q = MClockOpClassQueue({"client": (0.0, 500.0, 0.0),
                                "recovery": (0.0, 1.0, 0.0)})
        for i in range(100):
            q.enqueue("client", 63, 1 << 20, ("c", i))
            q.enqueue("recovery", 3, 0, ("r", i))
        out = drain(q, now=time.monotonic() + 1000, limit=100)
        c = sum(1 for tag, _ in out if tag == "c")
        assert c >= 60  # weights stay the dominant signal

    def test_per_class_fifo(self):
        q = MClockOpClassQueue()
        for i in range(10):
            q.enqueue("client", 0, 0, i)
        assert drain(q, now=time.monotonic() + 5) == list(range(10))

    def test_strict_band(self):
        q = MClockOpClassQueue()
        q.enqueue("client", 0, 0, "normal")
        q.enqueue_strict("peering", 255, "urgent")
        assert q.dequeue(time.monotonic() + 5) == "urgent"


class TestFactoryAndShards:
    def test_make_op_queue(self):
        assert isinstance(make_op_queue(Config()), WeightedPriorityQueue)
        conf = Config({"osd_op_queue": "mclock_opclass",
                       "osd_op_queue_mclock_client_res": 5.0})
        q = make_op_queue(conf)
        assert isinstance(q, MClockOpClassQueue)
        assert q.info["client"] == (5.0, 500.0, 0.0)
        assert make_op_queue(Config({"osd_op_queue": "fifo"})) is None
        with pytest.raises(ValueError):
            make_op_queue(Config({"osd_op_queue": "lottery"}))

    def test_sharded_wq_per_key_ordering(self):
        wq = QosShardedOpWQ("t", 2, WeightedPriorityQueue)
        wq.start()
        seen = {"a": [], "b": []}
        lock = threading.Lock()

        def work(key, i):
            with lock:
                seen[key].append(i)

        try:
            for i in range(50):
                wq.queue("pga", work, "a", i)
                wq.queue("pgb", work, "b", i, klass="recovery", priority=3)
            wq.drain()
            assert seen["a"] == list(range(50))
            assert seen["b"] == list(range(50))
        finally:
            wq.stop()

    def test_stop_drains_pending_work(self):
        """stop() must finish queued work first (ShardedThreadPool
        sentinel parity) — dropping it would strand client replies."""
        done = []
        wq = QosShardedOpWQ("t", 1, WeightedPriorityQueue)
        wq.start()
        for i in range(200):
            wq.queue("k", done.append, i)
        wq.stop()
        assert done == list(range(200))

    def test_stop_drains_through_mclock_limits(self):
        done = []
        wq = QosShardedOpWQ(
            "t", 1, lambda: MClockOpClassQueue(
                {"recovery": (0.0, 1.0, 2.0)}))   # 2 ops/s limit
        wq.start()
        for i in range(6):
            wq.queue("k", done.append, i, klass="recovery")
        wq.stop()   # must not wait ~3s for limit slots
        assert done == list(range(6))

    def test_mclock_reactivated_class_cannot_evade_weight(self):
        """A class that drains between single ops (a trickler) must not
        jump ahead of a heavier class: debt clamps to now, but the next
        tag still advances by 1/weight."""
        q = MClockOpClassQueue({"client": (0.0, 500.0, 0.0),
                                "recovery": (0.0, 1.0, 0.0)})
        q.enqueue("recovery", 0, 0, "r0")
        assert q.dequeue(time.monotonic() + 5) == "r0"   # drain
        q.enqueue("recovery", 0, 0, "r1")   # reactivation
        q.enqueue("client", 0, 0, "c0")
        # both eligible: the client's weight tag is nearer to now
        assert q.dequeue(time.monotonic() + 5) == "c0"
        assert q.dequeue(time.monotonic() + 5) == "r1"

    def test_mclock_idle_class_reactivates_fresh(self):
        q = MClockOpClassQueue({"recovery": (0.0, 1.0, 0.0),
                                "client": (0.0, 500.0, 0.0)})
        t0 = time.monotonic()
        for i in range(50):   # builds ~50s of p_tag debt at weight 1
            q.enqueue("recovery", 0, 0, ("r", i))
        assert len(drain(q, now=t0 + 1000)) == 50
        # class drained -> debt forgotten; a fresh op competes at `now`
        q.enqueue("recovery", 0, 0, ("r", "fresh"))
        assert q.dequeue(time.monotonic() + 0.001) == ("r", "fresh")

    def test_idle_shard_stays_heartbeat_healthy(self):
        from ceph_tpu.common.heartbeat_map import HeartbeatMap
        hb = HeartbeatMap()
        wq = QosShardedOpWQ("t", 1, WeightedPriorityQueue, hbmap=hb,
                            grace=0.3)
        wq.start()
        try:
            wq.queue("k", lambda: None)
            wq.drain()
            time.sleep(0.8)   # idle well past the grace period
            assert hb.is_healthy(), hb.unhealthy_workers() \
                if hasattr(hb, "unhealthy_workers") else "unhealthy"
        finally:
            wq.stop()

    def test_cluster_runs_on_mclock(self):
        """End-to-end: a cluster configured with the dmclock queue still
        serves client IO correctly."""
        from .cluster_util import MiniCluster
        FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02,
                "osd_op_queue": "mclock_opclass"}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "qos", size=2, pg_num=4)
            io = client.open_ioctx("qos")
            for i in range(10):
                io.write_full("obj%d" % i, b"payload-%d" % i)
            for i in range(10):
                assert io.read("obj%d" % i) == b"payload-%d" % i
        finally:
            cluster.stop()
