"""Per-client/per-pool perf-query attribution: OSD engine bounds and
filters, attribution integrity across client reconnects, the mgr
module's cluster-wide merge + ageout, counter-reset handling in the
aggregator's derivations (bounced-daemon regression), the iotop /
`osd perf query` CLI against a live cluster, POOL_SLO_VIOLATION
raise/clear through the mon, and the exposition discipline of the new
labeled series (bounded top-N, hostile labels, appear-then-age-out).
"""

from __future__ import annotations

import json
import time
import types

import pytest

from ceph_tpu.mgr import (MetricsAggregator, PerfQueryModule,
                          PrometheusModule, StatusModule)
from ceph_tpu.osd.perf_query import (PQ_LAT_BUCKETS_US,
                                     PerfQueryEngine)

from .cluster_util import MiniCluster, wait_until
from .cluster_util import lint_exposition as _lint_exposition

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02,
        "mgr_stats_period": 0.25}


def _msg(client_id=1, session="cafebabe" * 4, oid="obj",
         ops=None):
    """A fake MOSDOp carrying just what the engine keys/accounts by."""
    return types.SimpleNamespace(
        client_id=client_id, session=session, oid=oid,
        ops=ops if ops is not None else [("write_full", b"x" * 64)])


# -- OSD engine: bounds, filters, attribution integrity ----------------

class TestEngine:
    def test_key_table_bounded_under_churn(self):
        """10x max_keys distinct clients churn through one query: the
        table never exceeds the bound, LRU evicts oldest-updated
        first, and every displacement is counted."""
        eng = PerfQueryEngine()
        eng.add_query(1, {"key_by": ["client"], "max_keys": 32})
        for i in range(320):
            eng.account(_msg(client_id=i, session="%032x" % i),
                        "p", "1.0", False, 100, 0, 0.001, now=float(i))
        q = eng._queries[1]
        assert len(q.table) <= 32
        assert q.evictions == 320 - 32
        # the survivors are exactly the most recent 32 clients
        survivors = {k[0] for k in q.table}
        expected = {"client.%d:%s" % (i, ("%032x" % i)[:8])
                    for i in range(288, 320)}
        assert survivors == expected

    def test_add_query_idempotent_redefine_resets(self):
        """Re-adding the same spec (the mgr's osdmap re-broadcast)
        must NOT reset an accumulating table; a changed spec must."""
        eng = PerfQueryEngine()
        eng.add_query(1, {"key_by": ["client", "pool"]})
        eng.account(_msg(), "p", "1.0", False, 10, 0, 0.001)
        assert len(eng._queries[1].table) == 1
        eng.add_query(1, {"key_by": ["client", "pool"]})
        assert len(eng._queries[1].table) == 1    # preserved
        eng.add_query(1, {"key_by": ["pool"]})
        assert len(eng._queries[1].table) == 0    # redefined

    def test_pool_and_prefix_filters(self):
        eng = PerfQueryEngine()
        eng.add_query(1, {"key_by": ["client"], "pool": "gold"})
        eng.add_query(2, {"key_by": ["client"],
                          "object_prefix": "img-"})
        eng.account(_msg(oid="img-7"), "gold", "1.0", False,
                    10, 0, 0.001)
        eng.account(_msg(oid="doc-7"), "silver", "1.0", False,
                    10, 0, 0.001)
        assert len(eng._queries[1].table) == 1    # only the gold op
        assert len(eng._queries[2].table) == 1    # only the img- op

    def test_fresh_session_nonce_is_a_fresh_key(self):
        """Attribution integrity: a reconnect reusing client_id 7 with
        a NEW session nonce must not merge into the dead process's
        key."""
        eng = PerfQueryEngine()
        eng.add_query(1, {"key_by": ["client"]})
        eng.account(_msg(client_id=7, session="a" * 32), "p", "1.0",
                    False, 10, 0, 0.001)
        eng.account(_msg(client_id=7, session="b" * 32), "p", "1.0",
                    False, 20, 0, 0.001)
        keys = sorted(k[0] for k in eng._queries[1].table)
        assert keys == ["client.7:" + "a" * 8,
                        "client.7:" + "b" * 8]
        stats = {k[0]: st for k, st in eng._queries[1].table.items()}
        assert stats["client.7:" + "a" * 8].wr_bytes == 10
        assert stats["client.7:" + "b" * 8].wr_bytes == 20

    def test_read_write_split_and_histogram(self):
        eng = PerfQueryEngine()
        eng.add_query(1, {"key_by": ["client", "pool"]})
        eng.account(_msg(), "p", "1.0", False, 100, 0, 0.001)
        eng.account(_msg(), "p", "1.0", True, 0, 4096, 0.004)
        (key, st), = eng._queries[1].table.items()
        assert key == ("client.1:cafebabe", "p")
        assert (st.ops, st.rd_ops, st.wr_ops) == (2, 1, 1)
        assert st.wr_bytes == 100 and st.rd_bytes == 4096
        assert st.lat_count == 2
        assert sum(st.lat_hist) == 2
        # 1ms = 1000us lands in the bucket whose edge first covers it
        idx = next(i for i, e in enumerate(PQ_LAT_BUCKETS_US)
                   if 1000 <= e)
        assert st.lat_hist[idx] == 1
        row = eng._queries[1].dump()["keys"][0]
        assert row["k"] == ["client.1:cafebabe", "p"]
        assert row["lat_count"] == 2

    def test_idle_keys_pruned_at_dump(self):
        eng = PerfQueryEngine()
        eng.key_age = 5.0
        eng.add_query(1, {"key_by": ["client"]})
        eng.account(_msg(client_id=1), "p", "1.0", False, 1, 0,
                    0.001, now=100.0)
        eng.account(_msg(client_id=2), "p", "1.0", False, 1, 0,
                    0.001, now=104.0)
        dump = eng.dump(now=107.0)   # client 1 idle 7s > 5s
        labels = [r["k"][0] for r in dump["1"]["keys"]]
        assert len(labels) == 1 and labels[0].startswith("client.2:")


# -- aggregator counter-reset regression (simulated OSD bounce) --------

class TestCounterReset:
    def _agg(self):
        return MetricsAggregator(history=32, stale_after=100.0,
                                 window=100.0)

    def test_rate_clamped_and_rederived_after_bounce(self):
        """osd.0 bounces mid-window: its counter restarts from zero.
        The rate must never go negative and must derive from the
        post-reset segment only."""
        m = self._agg()
        for t, v in ((0.0, 1000), (1.0, 2000), (2.0, 100),
                     (3.0, 300)):
            m.record("osd.0", {"osd": {"op_w": v}}, now=t)
        # post-reset segment: (300 - 100) / (3 - 2)
        assert m.rate("osd.0", "osd", "op_w", now=3.0) == 200.0

    def test_rate_zero_when_reset_is_newest_sample(self):
        m = self._agg()
        for t, v in ((0.0, 1000), (1.0, 2000), (2.0, 5)):
            m.record("osd.0", {"osd": {"op_w": v}}, now=t)
        assert m.rate("osd.0", "osd", "op_w", now=2.0) == 0.0

    def test_time_avg_never_negative_across_bounce(self):
        """The bounced daemon restarted with a SMALLER sum but a
        sample count the naive delta reads as positive — the old
        derivation returned a negative latency."""
        m = self._agg()
        m.record("osd.0", {"osd": {"lat": {"avgcount": 100,
                                           "sum": 50.0}}}, now=0.0)
        m.record("osd.0", {"osd": {"lat": {"avgcount": 120,
                                           "sum": 0.6}}}, now=1.0)
        got = m.time_avg("osd.0", "osd", "lat", now=1.0)
        assert got == pytest.approx(0.6 / 120)
        assert got >= 0.0

    def test_percentiles_use_fresh_fills_after_bounce(self):
        m = self._agg()
        m.record("osd.0",
                 {"osd": {"h": {"count": 100, "sum": 1,
                                "buckets": [0, 100, 0, 0]}}}, now=0.0)
        m.record("osd.0",
                 {"osd": {"h": {"count": 8, "sum": 1,
                                "buckets": [0, 0, 0, 8]}}}, now=1.0)
        q = m.percentiles("osd.0", "osd", "h", (0.5,), window=10.0,
                          now=1.0)
        # negative windowed delta -> the newest (post-reset) fills are
        # the distribution: all mass in bucket 3 (bounds 2,4,8,16)
        assert q[0.5] > 8.0


# -- mgr module merge: windowed views, ageout, SLO burn ----------------

class _Conf:
    def get_val(self, name):
        raise KeyError(name)


class _FakeMgr:
    def __init__(self, metrics):
        self.ctx = types.SimpleNamespace(conf=_Conf())
        self.metrics = metrics
        self.modules: dict = {}
        self.health: dict = {}
        self.name = "mgr.t"
        self.mon_client = None
        self.sent: list = []
        self.msgr = types.SimpleNamespace(
            send_message=lambda msg, addr: self.sent.append((msg,
                                                             addr)))

    def get_state(self, name):
        if name == "metrics":
            return self.metrics
        if name == "osd_map":
            return None
        if name == "health":
            return dict(self.health)
        if name == "perf_counters":
            return {}
        raise KeyError(name)

    def set_module_health(self, module, checks):
        if checks:
            self.health[module] = dict(checks)
        else:
            self.health.pop(module, None)


def _payload(qid, key_by, rows):
    """An OSD perf_query dump: rows = [(key tuple, ops, wr_bytes,
    lat_count, hist_bucket_index)]"""
    keys = []
    for key, ops, wr_bytes, lat_count, bucket in rows:
        hist = [0] * (len(PQ_LAT_BUCKETS_US) + 1)
        hist[bucket] = lat_count
        keys.append({"k": list(key), "ops": ops, "rd_ops": 0,
                     "wr_ops": ops, "rd_bytes": 0,
                     "wr_bytes": wr_bytes, "lat_sum": 0.001 * ops,
                     "lat_count": lat_count, "lat_hist": hist})
    return {str(qid): {"key_by": list(key_by),
                       "buckets_us": list(PQ_LAT_BUCKETS_US),
                       "evictions": 0, "keys": keys}}


class TestMgrMerge:
    def _module(self):
        metrics = MetricsAggregator(history=64, stale_after=100.0,
                                    window=10.0)
        mgr = _FakeMgr(metrics)
        mod = PerfQueryModule(mgr)
        mgr.modules["perf_query"] = mod
        return mgr, metrics, mod

    def test_views_sum_rates_across_osds(self):
        mgr, metrics, mod = self._module()
        key = ("client.1:aaaa", "data")
        for osd, (o0, o1) in (("osd.0", (10, 30)),
                              ("osd.1", (5, 15))):
            metrics.record(osd, {}, daemon_type="osd", now=100.0,
                           perf_query=_payload(
                               1, ["client", "pool"],
                               [(key, o0, o0 * 100, o0, 12)]))
            metrics.record(osd, {}, daemon_type="osd", now=102.0,
                           perf_query=_payload(
                               1, ["client", "pool"],
                               [(key, o1, o1 * 100, o1, 12)]))
        rows = mod.views(window=10.0, now=102.0)[1]["rows"]
        # (30-10)/2 + (15-5)/2 = 15 ops/s summed across both OSDs
        assert rows[key]["ops_rate"] == pytest.approx(15.0)
        assert rows[key]["wr_Bps"] == pytest.approx(1500.0)
        top = mod.top_clients(now=102.0)
        assert top[0]["client"] == "client.1:aaaa"
        assert top[0]["pool"] == "data"
        assert top[0]["p99_ms"] > 0

    def test_osd_bounce_counts_as_fresh_window(self):
        """An OSD restart resets its key table: the post-bounce value
        is the fresh delta, never a negative contribution."""
        mgr, metrics, mod = self._module()
        key = ("client.1:aaaa", "data")
        metrics.record("osd.0", {}, daemon_type="osd", now=100.0,
                       perf_query=_payload(1, ["client", "pool"],
                                           [(key, 1000, 10, 10, 5)]))
        metrics.record("osd.0", {}, daemon_type="osd", now=102.0,
                       perf_query=_payload(1, ["client", "pool"],
                                           [(key, 8, 80, 8, 5)]))
        rows = mod.views(window=10.0, now=102.0)[1]["rows"]
        assert rows[key]["ops_rate"] == pytest.approx(8 / 2.0)

    def test_stale_client_ages_out_of_views(self):
        """A client that stops issuing ops leaves the merged views
        after mgr_perf_query_client_age even while its key still rides
        the OSD dumps (unchanged counters)."""
        mgr, metrics, mod = self._module()
        key = ("client.9:dead", "data")
        pay = _payload(1, ["client", "pool"], [(key, 50, 500, 50, 5)])
        metrics.record("osd.0", {}, daemon_type="osd", now=100.0,
                       perf_query=_payload(1, ["client", "pool"],
                                           [(key, 10, 100, 10, 5)]))
        metrics.record("osd.0", {}, daemon_type="osd", now=101.0,
                       perf_query=pay)
        assert key in mod.views(window=10.0, now=101.0)[1]["rows"]
        # the client vanishes: counters freeze, reports keep coming
        for i in range(2, 15):
            metrics.record("osd.0", {}, daemon_type="osd",
                           now=100.0 + i, perf_query=pay)
        rows = mod.views(window=10.0, now=114.0).get(1, {}) \
            .get("rows", {})
        assert key not in rows

    def test_slo_raise_then_clear(self):
        mgr, metrics, mod = self._module()
        mod.slo_targets = {"data": (0.001, 0.9)}   # 1ms, 99.. 90%
        # all latency mass in bucket 12 (lower bound 2^12 us = 4.1ms
        # > 1ms threshold) -> violation fraction 1.0, burn 10x
        metrics.record("osd.0", {}, daemon_type="osd", now=100.0,
                       perf_query=_payload(2, ["pool"],
                                           [(("data",), 10, 100,
                                             10, 12)]))
        metrics.record("osd.0", {}, daemon_type="osd", now=102.0,
                       perf_query=_payload(2, ["pool"],
                                           [(("data",), 40, 400,
                                             40, 12)]))
        state = mod.evaluate_slo(now=102.0)
        assert state["data"]["violation_fraction"] == 1.0
        assert state["data"]["burn_ratio"] == pytest.approx(10.0)
        checks = mgr.health.get("perf_query", {})
        assert "POOL_SLO_VIOLATION" in checks
        assert "pool 'data'" in checks["POOL_SLO_VIOLATION"][
            "detail"][0]
        # burn within budget -> the check clears
        mod.slo_targets = {"data": (10.0, 0.9)}    # 10s threshold
        state = mod.evaluate_slo(now=102.0)
        assert state["data"]["violation_fraction"] == 0.0
        assert "perf_query" not in mgr.health
        status = mod.slo_status()
        assert status["alerting"] is False

    def test_prometheus_exports_bounded_top_n(self):
        """Only prom_top_n client rows reach the page — client labels
        are unbounded-cardinality input."""
        mgr, metrics, mod = self._module()
        mod.prom_top_n = 3
        now = time.monotonic()
        rows0 = [(("client.%d:aaaa" % i, "data"), 10 * (i + 1),
                  100, 10, 5) for i in range(8)]
        rows1 = [(("client.%d:aaaa" % i, "data"), 20 * (i + 1),
                  200, 20, 5) for i in range(8)]
        metrics.record("osd.0", {}, daemon_type="osd", now=now - 2,
                       perf_query=_payload(1, ["client", "pool"],
                                           rows0))
        metrics.record("osd.0", {}, daemon_type="osd", now=now,
                       perf_query=_payload(1, ["client", "pool"],
                                           rows1))
        prom = PrometheusModule(mgr)
        mgr.modules["prometheus"] = prom
        text = prom.render()
        n = text.count("ceph_client_op_rate{")
        assert n == 3, text
        # the top-3 by ops/s are the highest-indexed clients
        for i in (5, 6, 7):
            assert 'client="client.%d:aaaa"' % i in text


# -- live cluster: end-to-end attribution ------------------------------

@pytest.fixture(scope="module")
def pq_cluster():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    mgr = cluster.start_mgr(modules=(PerfQueryModule, StatusModule,
                                     PrometheusModule))
    client = cluster.client()
    pool_id = cluster.create_replicated_pool(client, "attrpool",
                                             size=2, pg_num=8)
    assert cluster.wait_clean(pool_id)
    assert wait_until(lambda: mgr.osdmap is not None, timeout=10)
    yield cluster, mgr, client, pool_id
    cluster.stop()


def _load(client, n=24, size=4096):
    io = client.open_ioctx("attrpool")
    for i in range(n):
        io.write_full("pq-%d" % i, b"w" * size)
    for i in range(0, n, 3):
        assert io.read("pq-%d" % i) == b"w" * 4096


class TestLiveAttribution:
    def test_default_queries_reach_every_osd(self, pq_cluster):
        cluster, mgr, _, _ = pq_cluster
        assert wait_until(
            lambda: all(o.perf_query.active
                        for o in cluster.osds.values()), timeout=15)
        specs = cluster.osds[0].perf_query.list_queries()
        key_bys = sorted(tuple(s["key_by"]) for s in specs.values())
        assert ("client", "pool") in key_bys
        assert ("pool",) in key_bys

    def test_iotop_attributes_live_load(self, pq_cluster):
        cluster, mgr, client, _ = pq_cluster
        _load(client)
        label = "client.%d:%s" % (client.client_id,
                                  client.session[:8])

        def sees_client():
            _load(client, n=6)
            return any(r["client"] == label and r["pool"] == "attrpool"
                       for r in mgr.modules["perf_query"]
                       .top_clients(window=30.0))
        assert wait_until(sees_client, timeout=20, interval=0.3)
        rc, out, _ = mgr.module_command(
            {"prefix": "iotop", "window": 30.0})
        assert rc == 0
        assert label in out and "CLIENT" in out

    def test_status_top_clients_line(self, pq_cluster):
        cluster, mgr, client, _ = pq_cluster
        _load(client, n=12)

        def status_has_line():
            _load(client, n=6)
            rc, out, _ = mgr.module_command({"prefix": "status"})
            assert rc == 0
            return "top clients:" in out
        assert wait_until(status_has_line, timeout=20, interval=0.3)

    def test_reconnect_fresh_session_not_merged_live(self, pq_cluster):
        """Two incarnations of client_id 77 (fresh session nonce each)
        write through the same cluster: the OSD key tables keep them
        apart."""
        from ceph_tpu.client.rados import RadosClient
        from ceph_tpu.common.context import Context
        cluster, mgr, _, _ = pq_cluster
        sessions = []
        for _ in range(2):
            c = RadosClient(cluster.monmap,
                            Context(cluster.conf_overrides,
                                    name="client.77"), client_id=77)
            c.connect()
            try:
                sessions.append(c.session[:8])
                io = c.open_ioctx("attrpool")
                for i in range(8):
                    io.write_full("re-%d" % i, b"r" * 2048)
            finally:
                c.shutdown()
        assert sessions[0] != sessions[1]

        def both_keys():
            labels = set()
            for osd in cluster.osds.values():
                for dump in osd.perf_query.dump().values():
                    for row in dump["keys"]:
                        if row["k"] and str(row["k"][0]) \
                                .startswith("client.77:"):
                            labels.add(row["k"][0])
            return {"client.77:" + s for s in sessions} <= labels
        assert wait_until(both_keys, timeout=15)

    def test_cli_iotop_and_perf_query(self, pq_cluster, capsys):
        from ceph_tpu.tools import ceph_cli
        cluster, mgr, client, _ = pq_cluster
        _load(client, n=12)
        rc = ceph_cli.main(["--asok", cluster.mgr_asok, "iotop",
                            "--period", "5", "--count", "1"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "CLIENT" in out and "p99_ms" in out
        # add a prefix-filtered query, see it land on the OSDs, rm it
        rc = ceph_cli.main(["--asok", cluster.mgr_asok, "osd", "perf",
                            "query", "add", "client",
                            "--object-prefix", "pq-"])
        out = capsys.readouterr().out
        assert rc == 0
        qid = json.loads(out)["query_id"]
        assert wait_until(
            lambda: all(str(qid) in o.perf_query.list_queries()
                        for o in cluster.osds.values()), timeout=15)
        rc = ceph_cli.main(["--asok", cluster.mgr_asok, "osd", "perf",
                            "query", "ls"])
        out = capsys.readouterr().out
        assert rc == 0 and str(qid) in json.loads(out)["queries"]
        rc = ceph_cli.main(["--asok", cluster.mgr_asok, "osd", "perf",
                            "query", "rm", str(qid)])
        out = capsys.readouterr().out
        assert rc == 0 and json.loads(out)["removed"] is True
        assert wait_until(
            lambda: all(str(qid) not in o.perf_query.list_queries()
                        for o in cluster.osds.values()), timeout=15)

    def test_slo_violation_raises_and_clears_through_mon(
            self, pq_cluster):
        """An unreachable 2us target turns all real ops into
        violations -> POOL_SLO_VIOLATION raises on the mgr AND the
        mon; a sane target clears both."""
        cluster, mgr, client, _ = pq_cluster
        mod = mgr.modules["perf_query"]
        mod.slo_targets = {"attrpool": (2e-6, 0.5)}
        try:
            def raised():
                _load(client, n=6)
                return "POOL_SLO_VIOLATION" in mgr.get_state("health")
            assert wait_until(raised, timeout=20, interval=0.3)

            def mon_raised():
                _, _, data = client.mon_command({"prefix": "health"})
                return "POOL_SLO_VIOLATION" in data["checks"]
            assert wait_until(mon_raised, timeout=15)
            rc, out, _ = mgr.module_command({"prefix": "slo status"})
            assert rc == 0
            assert json.loads(out)["alerting"] is True
            # mon carry-until-first-report: a fresh leader with no mgr
            # report yet keeps the committed verdict
            hm = cluster.leader().healthmon
            hm._slo_report = None
            hm.recompute()

            def still_raised():
                _, _, data = client.mon_command({"prefix": "health"})
                return "POOL_SLO_VIOLATION" in data["checks"]
            assert still_raised()
        finally:
            mod.slo_targets = {"attrpool": (1000.0, 0.5)}

        def cleared():
            _load(client, n=6)
            _, _, data = client.mon_command({"prefix": "health"})
            return "POOL_SLO_VIOLATION" not in data["checks"] and \
                "POOL_SLO_VIOLATION" not in mgr.get_state("health")
        assert wait_until(cleared, timeout=20, interval=0.3)

    def test_prometheus_live_page_has_attribution_series(
            self, pq_cluster):
        cluster, mgr, client, _ = pq_cluster
        prom = mgr.modules["prometheus"]

        def on_page():
            _load(client, n=6)
            return "ceph_client_op_rate{" in prom.render()
        assert wait_until(on_page, timeout=20, interval=0.3)
        text = prom.render()
        assert "ceph_client_byte_rate{" in text
        _lint_exposition(text)

    def test_hostile_labels_roundtrip_then_age_out(self, pq_cluster):
        """Hostile client/pool names (spaces, quotes, backslashes,
        UTF-8, raw newline) injected through the same ingest path the
        OSD reports use must round-trip escaped on the FULL live page
        — and leave it when the OSD-side prune drops the key."""
        cluster, mgr, client, _ = pq_cluster
        prom = mgr.modules["prometheus"]
        mod = mgr.modules["perf_query"]
        hostile_client = 'cli "ent\\ß\n77'
        hostile_pool = 'pøol "q\\'
        key = (hostile_client, hostile_pool)
        now = time.monotonic()
        mgr.metrics.record(
            "osd.96", {"osd": {}}, daemon_type="osd", now=now - 1.0,
            perf_query=_payload(1, ["client", "pool"],
                                [(key, 10, 100, 10, 5)]))
        mgr.metrics.record(
            "osd.96", {"osd": {}}, daemon_type="osd", now=now,
            perf_query=_payload(1, ["client", "pool"],
                                [(key, 9000, 9000, 9000, 5)]))
        # a hostile pool name through the SLO series too
        mod._slo_state = {hostile_pool: {"threshold_ms": 1.0,
                                         "objective": 0.9,
                                         "samples": 1,
                                         "violation_fraction": 0.5,
                                         "burn_ratio": 5.0}}
        try:
            text = prom.render()
            esc_client = (hostile_client.replace("\\", "\\\\")
                          .replace('"', '\\"').replace("\n", "\\n"))
            esc_pool = (hostile_pool.replace("\\", "\\\\")
                        .replace('"', '\\"'))
            assert 'client="%s"' % esc_client in text
            assert 'ceph_pool_slo_burn_ratio{pool="%s"}' % esc_pool \
                in text
            _lint_exposition(text)
            # the OSD prunes the idle key from its dumps (empty key
            # table keeps riding the reports): the series leave the
            # page — appear-then-age-out
            for dt in (0.1, 0.2):
                mgr.metrics.record(
                    "osd.96", {"osd": {}}, daemon_type="osd",
                    now=now + dt,
                    perf_query=_payload(1, ["client", "pool"], []))
        finally:
            mod._slo_state = {}
            mgr.metrics.remove("osd.96")
        text = prom.render()
        assert 'client="%s"' % esc_client not in text
