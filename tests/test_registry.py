"""Registry behavior + failure-mode tests.

Modeled on src/test/erasure-code/TestErasureCodePlugin.cc and its broken
plugin fixtures (FailToInitialize / FailToRegister / MissingVersion /
MissingEntryPoint).
"""

import errno

import pytest

from ceph_tpu import registry
from ceph_tpu.models.base import ErasureCodeError
from ceph_tpu.registry import (ErasureCodePlugin, ErasureCodePluginRegistry,
                               __erasure_code_version__)


@pytest.fixture
def reg():
    # fresh registry instance, isolated from the singleton
    return ErasureCodePluginRegistry()


def test_unknown_plugin(reg):
    with pytest.raises(ErasureCodeError) as e:
        reg.factory("does_not_exist", {})
    assert e.value.errno == errno.ENOENT


def test_duplicate_add(reg):
    p = ErasureCodePlugin()
    reg.add("p", p)
    with pytest.raises(ErasureCodeError) as e:
        reg.add("p", ErasureCodePlugin())
    assert e.value.errno == errno.EEXIST
    assert reg.get("p") is p


def test_version_mismatch(reg):
    class Stale(ErasureCodePlugin):
        version = "0.0.0-stale"
    reg.loaders["stale"] = Stale
    with pytest.raises(ErasureCodeError) as e:
        reg.load("stale")
    assert e.value.errno == errno.EXDEV


def test_fail_to_initialize(reg):
    class Broken(ErasureCodePlugin):
        def factory(self, profile, errors=None):
            raise ErasureCodeError(errno.ESHUTDOWN, "init failed")
    reg.loaders["broken"] = Broken
    with pytest.raises(ErasureCodeError) as e:
        reg.factory("broken", {})
    assert e.value.errno == errno.ESHUTDOWN


def test_fail_to_register(reg):
    reg.loaders["liar"] = lambda: "not a plugin"
    with pytest.raises(ErasureCodeError) as e:
        reg.load("liar")
    assert e.value.errno == errno.ENOENT


def test_preload_comma_list(reg):
    reg.preload("jerasure,example")
    assert reg.get("jerasure") is not None
    assert reg.get("example") is not None


def test_technique_dispatch_enoent(reg):
    with pytest.raises(ErasureCodeError) as e:
        reg.factory("jerasure", {"technique": "no_such_technique"})
    assert e.value.errno == errno.ENOENT


def test_profile_echo():
    profile = {"technique": "reed_sol_van", "k": "4", "m": "2"}
    codec = registry.factory("jerasure", profile)
    # resolved defaults are echoed back into the profile (registry contract)
    assert profile["w"] == "8"
    assert codec.get_profile() is profile


def test_singleton():
    assert ErasureCodePluginRegistry.instance() is \
        ErasureCodePluginRegistry.instance()


def test_example_plugin_roundtrip():
    import numpy as np
    codec = registry.factory("example", {})
    raw = bytes(range(200)) * 5
    enc = codec.encode({0, 1, 2}, raw)
    dec = codec.decode({0}, {1: enc[1], 2: enc[2]})
    assert np.array_equal(dec[0], enc[0])
    # cost-aware selection prefers the cheap chunks
    assert codec.minimum_to_decode_with_cost(
        {2}, {0: 1, 1: 9, 2: 1}) == {2}


def test_malformed_int_profile_rejected():
    # reference to_int fails init with -EINVAL on malformed ints
    with pytest.raises(ErasureCodeError) as e:
        registry.factory("jerasure", {"technique": "reed_sol_van",
                                      "k": "1o", "m": "2", "w": "8"})
    assert e.value.errno == errno.EINVAL


def test_invalid_geometry_rejected():
    for prof in ({"technique": "reed_sol_van", "k": "4", "m": "0"},
                 {"technique": "cauchy_good", "k": "4", "m": "2", "w": "33"},
                 {"technique": "cauchy_good", "k": "4", "m": "2",
                  "packetsize": "0"},
                 {"technique": "reed_sol_van", "k": "300", "m": "2",
                  "w": "8"}):
        with pytest.raises(ErasureCodeError) as e:
            registry.factory("jerasure", dict(prof))
        assert e.value.errno == errno.EINVAL, prof


def test_cauchy_unusual_w_accepted():
    # cauchy supports any 2 <= w <= 32 (not just {8,16,32})
    codec = registry.factory("jerasure", {"technique": "cauchy_good",
                                          "k": "4", "m": "2", "w": "20",
                                          "packetsize": "4"})
    assert codec.w == 20


def test_example_cost_recovers_expensive_chunk():
    # all chunks available, one is expensive -> recover it from the others
    codec = registry.factory("example", {})
    assert codec.minimum_to_decode_with_cost(
        {0}, {0: 9, 1: 1, 2: 1}) == {1, 2}
