"""Tools: benchmark CLI/output contract, probe tool, non-regression corpora.

Models the reference's usage of its EC tool suite (canonical invocations in
src/erasure-code/isa/README:36-46 and the compile-command footers of the
tool sources)."""

import re

import numpy as np
import pytest

from ceph_tpu.tools import erasure_code, erasure_code_benchmark, non_regression

OUT_RE = re.compile(r"^(\d+\.\d{6})\t(\d+)$")


def run_bench(argv):
    return erasure_code_benchmark.main(argv)


class TestBenchmarkTool:
    def _run(self, capsys, argv):
        code = run_bench(argv)
        out = capsys.readouterr().out.strip().splitlines()[-1]
        return code, out

    def test_encode_output_contract(self, capsys):
        code, out = self._run(capsys, [
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=2", "-P", "m=1", "-s", "4096", "-i", "3"])
        assert code == 0
        m = OUT_RE.match(out)
        assert m, out
        assert int(m.group(2)) == 3 * (4096 // 1024)

    def test_decode_random(self, capsys):
        code, out = self._run(capsys, [
            "-w", "decode", "-e", "2",
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=4", "-P", "m=2", "-s", "4096", "-i", "2"])
        assert code == 0
        assert OUT_RE.match(out), out

    def test_decode_exhaustive_verifies(self, capsys):
        code, out = self._run(capsys, [
            "-w", "decode", "-e", "2", "-E", "exhaustive",
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=4", "-P", "m=2", "-s", "2048", "-i", "1"])
        assert code == 0
        assert OUT_RE.match(out), out

    def test_decode_erased_list(self, capsys):
        code = run_bench([
            "-w", "decode", "--erased", "0", "--erased", "3",
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=3", "-P", "m=2", "-s", "2048", "-i", "2"])
        out = capsys.readouterr().out
        assert code == 0
        assert "(0)" in out and "(3)" in out  # display_chunks markers

    def test_batched_encode(self, capsys):
        code, out = self._run(capsys, [
            "-p", "jax_tpu", "-P", "technique=reed_sol_van",
            "-P", "k=8", "-P", "m=3", "-s", "4096", "-i", "2",
            "--batch", "4"])
        assert code == 0
        m = OUT_RE.match(out)
        assert m and int(m.group(2)) == 2 * 4 * (4096 // 1024)

    def test_exhaustive_with_erased(self, capsys):
        code, out = self._run(capsys, [
            "-w", "decode", "-E", "exhaustive", "-e", "1", "--erased", "0",
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=3", "-P", "m=2", "-s", "2048", "-i", "1"])
        assert code == 0
        assert OUT_RE.match(out), out

    def test_decode_report_ignores_batch(self, capsys):
        # decode never batches; KiB must not be inflated by --batch
        code, out = self._run(capsys, [
            "-w", "decode", "-e", "1", "--batch", "4",
            "-p", "jerasure", "-P", "technique=reed_sol_van",
            "-P", "k=2", "-P", "m=1", "-s", "4096", "-i", "2"])
        assert code == 0
        assert int(OUT_RE.match(out).group(2)) == 2 * (4096 // 1024)

    def test_batch_unsupported_plugin(self, capsys, monkeypatch):
        # a codec without a batched path must yield a clean CLI error,
        # not a traceback
        from ceph_tpu.models import rs

        def boom(self, data):
            raise NotImplementedError
        monkeypatch.setattr(rs.ReedSolomonVandermonde, "encode_batch", boom)
        code = run_bench(["-p", "jerasure", "--batch", "2",
                          "-P", "technique=reed_sol_van",
                          "-P", "k=2", "-P", "m=1", "-s", "2048", "-i", "1"])
        assert code == 1
        assert "does not support --batch" in capsys.readouterr().err

    def test_bad_k_rejected(self, capsys):
        assert run_bench(["-P", "m=1"]) == 1

    def test_mismatched_km_rejected(self, capsys):
        # shec with c consumes different geometry; claim wrong m
        assert run_bench(["-p", "jerasure",
                          "-P", "technique=reed_sol_van",
                          "-P", "k=2", "-P", "m=1",
                          "-P", "mapping=_DD"]) in (0, 1)


class TestProbeTool:
    def test_plugin_exists(self):
        assert erasure_code.main(["--plugin_exists", "jerasure"]) == 0
        assert erasure_code.main(["--plugin_exists", "jax_tpu"]) == 0

    def test_plugin_missing(self, capsys):
        code = erasure_code.main(["--plugin_exists", "no_such_plugin"])
        assert code != 0
        assert "libec_no_such_plugin" in capsys.readouterr().err

    def test_display_all(self, capsys):
        code = erasure_code.main([
            "--all", "-P", "plugin=jerasure",
            "-P", "technique=reed_sol_van", "-P", "k=2", "-P", "m=2"])
        out = capsys.readouterr().out
        assert code == 0
        assert "get_data_chunk_count\t2" in out
        assert "get_coding_chunk_count\t2" in out
        assert "get_chunk_count\t4" in out
        assert re.search(r"get_chunk_size\(1024\)\t\d+", out)

    def test_plugin_mandatory(self, capsys):
        assert erasure_code.main(["--all"]) == 1
        assert "plugin=<plugin> is mandatory" in capsys.readouterr().err


PROFILES = [
    ("jerasure", ["technique=reed_sol_van", "k=2", "m=2"]),
    ("jerasure", ["technique=cauchy_good", "k=4", "m=2", "packetsize=64"]),
    ("jax_tpu", ["technique=reed_sol_van", "k=8", "m=3"]),
    ("shec", ["k=4", "m=3", "c=2"]),
    ("lrc", ["k=4", "m=2", "l=3"]),
]


class TestNonRegression:
    @pytest.mark.parametrize("plugin,params", PROFILES)
    def test_create_then_check(self, tmp_path, plugin, params):
        argv = ["--plugin", plugin, "--base", str(tmp_path),
                "--stripe-width", "3181"]
        for p in params:
            argv += ["--parameter", p]
        assert non_regression.main(argv + ["--create"]) == 0
        assert non_regression.main(argv + ["--check"]) == 0

    def test_check_detects_corruption(self, tmp_path, capsys):
        argv = ["--plugin", "jerasure", "--base", str(tmp_path),
                "--parameter", "technique=reed_sol_van",
                "--parameter", "k=2", "--parameter", "m=2"]
        assert non_regression.main(argv + ["--create"]) == 0
        # corrupt chunk 1 on disk
        nr = non_regression.NonRegression(
            non_regression.build_parser().parse_args(argv))
        path = nr.chunk_path(1)
        buf = bytearray(open(path, "rb").read())
        buf[0] ^= 0xFF
        open(path, "wb").write(bytes(buf))
        assert non_regression.main(argv + ["--check"]) == 1
        assert "encodes differently" in capsys.readouterr().err

    def test_check_without_corpus(self, tmp_path, capsys):
        argv = ["--plugin", "jerasure", "--base", str(tmp_path),
                "--parameter", "technique=reed_sol_van",
                "--parameter", "k=2", "--parameter", "m=2"]
        assert non_regression.main(argv + ["--check"]) == 1
        assert "FileNotFoundError" in capsys.readouterr().err

    def test_create_twice(self, tmp_path, capsys):
        argv = ["--plugin", "jerasure", "--base", str(tmp_path),
                "--parameter", "technique=reed_sol_van",
                "--parameter", "k=2", "--parameter", "m=2"]
        assert non_regression.main(argv + ["--create"]) == 0
        assert non_regression.main(argv + ["--create"]) == 1
        assert "FileExistsError" in capsys.readouterr().err

    def test_cross_plugin_bit_exactness(self, tmp_path):
        """jax_tpu must reproduce the CPU plugin's chunks bit-for-bit —
        the corpus contract that lets plugins interoperate on one pool."""
        argv_cpu = ["--plugin", "jerasure", "--base", str(tmp_path),
                    "--parameter", "technique=reed_sol_van",
                    "--parameter", "k=8", "--parameter", "m=3"]
        assert non_regression.main(argv_cpu + ["--create"]) == 0
        nr = non_regression.NonRegression(
            non_regression.build_parser().parse_args(argv_cpu))
        content = open(nr.content_path(), "rb").read()

        from ceph_tpu import registry
        tpu = registry.factory("jax_tpu", {"technique": "reed_sol_van",
                                           "k": "8", "m": "3"})
        encoded = tpu.encode(set(range(11)), content)
        for chunk in range(11):
            disk = np.frombuffer(open(nr.chunk_path(chunk), "rb").read(),
                                 dtype=np.uint8)
            np.testing.assert_array_equal(
                disk, np.asarray(encoded[chunk]),
                err_msg="chunk %d differs between plugins" % chunk)
