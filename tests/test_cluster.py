"""In-process cluster integration: replicated + EC pools end-to-end.

Models qa/standalone/erasure-code/test-erasure-code.sh at unit scale:
boot mon+osds on localhost, create pools per plugin, round-trip
objects, kill shard OSDs, verify degraded reads and recovery."""

import time

import numpy as np
import pytest

from ceph_tpu.osd.osd_map import CRUSH_ITEM_NONE, PGID

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0, "paxos_propose_interval": 0.02}


@pytest.fixture(scope="module")
def rep_cluster():
    cluster = MiniCluster(num_mons=1, num_osds=3,
                          conf_overrides=FAST).start()
    yield cluster
    cluster.stop()


class TestReplicatedPool:
    @pytest.fixture(scope="class")
    def ctx(self, rep_cluster):
        client = rep_cluster.client()
        rep_cluster.create_replicated_pool(client, "repdata", size=3,
                                           pg_num=8)
        ioctx = client.open_ioctx("repdata")
        return rep_cluster, client, ioctx

    def test_write_read_roundtrip(self, ctx):
        _, _, ioctx = ctx
        payload = b"hello replicated world" * 100
        ioctx.write_full("obj1", payload)
        assert ioctx.read("obj1") == payload
        assert ioctx.stat("obj1")["size"] == len(payload)

    def test_partial_write_and_append(self, ctx):
        _, _, ioctx = ctx
        ioctx.write_full("obj2", b"A" * 100)
        ioctx.write("obj2", b"BBB", offset=10)
        ioctx.append("obj2", b"TAIL")
        data = ioctx.read("obj2")
        assert data[10:13] == b"BBB"
        assert data.endswith(b"TAIL")
        assert len(data) == 104

    def test_xattr_omap(self, ctx):
        _, _, ioctx = ctx
        ioctx.write_full("obj3", b"x")
        ioctx.set_xattr("obj3", "color", b"blue")
        assert ioctx.get_xattr("obj3", "color") == b"blue"
        ioctx.omap_set("obj3", {"k1": b"v1", "k2": b"v2"})
        assert ioctx.omap_get("obj3")["k1"] == b"v1"

    def test_remove_and_enoent(self, ctx):
        _, _, ioctx = ctx
        ioctx.write_full("obj4", b"gone soon")
        ioctx.remove("obj4")
        with pytest.raises(Exception):
            ioctx.stat("obj4")

    def test_data_actually_replicated(self, ctx):
        cluster, client, ioctx = ctx
        ioctx.write_full("replcheck", b"R" * 512)
        m = client.osdmap
        raw = m.object_to_pg(ioctx.pool_id, "replcheck")
        pool = m.pools[ioctx.pool_id]
        pgid = pool.raw_pg_to_pg(raw)
        _, _, acting, _ = m.pg_to_up_acting_osds(pgid)
        assert len(acting) == 3
        for osd_id in acting:
            store = cluster.osds[osd_id].store
            data = store.read(("pg", str(pgid), -1), "replcheck")
            assert data == b"R" * 512


@pytest.fixture(scope="module")
def ec_cluster():
    cluster = MiniCluster(num_mons=1, num_osds=5,
                          conf_overrides=FAST).start()
    yield cluster
    cluster.stop()


EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "2", "m": "1", "crush-failure-domain": "host"}


class TestErasureCodedPool:
    @pytest.fixture(scope="class")
    def ctx(self, ec_cluster):
        client = ec_cluster.client()
        pool_id = ec_cluster.create_ec_pool(client, "ecdata",
                                            dict(EC_PROFILE), pg_num=8)
        assert ec_cluster.wait_clean(pool_id)
        ioctx = client.open_ioctx("ecdata")
        return ec_cluster, client, ioctx, pool_id

    def test_write_full_read_roundtrip(self, ctx):
        _, _, ioctx, _ = ctx
        rng = np.random.default_rng(3)
        payload = rng.integers(0, 256, size=40000, dtype=np.uint8) \
            .tobytes()
        ioctx.write_full("ecobj", payload)
        assert ioctx.read("ecobj") == payload
        assert ioctx.stat("ecobj")["size"] == len(payload)

    def test_chunks_are_striped_not_replicated(self, ctx):
        cluster, client, ioctx, pool_id = ctx
        payload = b"S" * 32768
        ioctx.write_full("stripecheck", payload)
        m = client.osdmap
        pool = m.pools[pool_id]
        pgid = pool.raw_pg_to_pg(m.object_to_pg(pool_id, "stripecheck"))
        _, _, acting, _ = m.pg_to_up_acting_osds(pgid)
        sizes = []
        for shard, osd_id in enumerate(acting):
            store = cluster.osds[osd_id].store
            data = store.read(("pg", str(pgid), shard), "stripecheck")
            sizes.append(len(data))
        # each shard holds ~1/k of the data, not a full copy
        assert all(s < len(payload) for s in sizes)
        assert sum(sizes) >= len(payload) * 3 // 2  # k=2,m=1 => 1.5x

    def test_partial_overwrite_rmw(self, ctx):
        _, _, ioctx, _ = ctx
        base = bytearray(b"0" * 20000)
        ioctx.write_full("rmwobj", bytes(base))
        ioctx.write("rmwobj", b"XYZ", offset=5000)
        base[5000:5003] = b"XYZ"
        assert ioctx.read("rmwobj") == bytes(base)

    def test_append(self, ctx):
        _, _, ioctx, _ = ctx
        ioctx.write_full("appobj", b"a" * 1000)
        ioctx.append("appobj", b"b" * 1000)
        data = ioctx.read("appobj")
        assert data == b"a" * 1000 + b"b" * 1000

    def test_degraded_read_after_osd_down(self, ctx):
        cluster, client, ioctx, pool_id = ctx
        payload = b"D" * 24000
        ioctx.write_full("degobj", payload)
        m = client.osdmap
        pool = m.pools[pool_id]
        pgid = pool.raw_pg_to_pg(m.object_to_pg(pool_id, "degobj"))
        _, _, acting, _ = m.pg_to_up_acting_osds(pgid)
        victim = acting[0]
        cluster.stop_osd(victim)
        # heartbeats detect, mon marks down; the client re-targets
        assert wait_until(
            lambda: cluster.leader().osdmon.osdmap.is_down(victim),
            timeout=15), "victim never marked down"
        client.mon_client.sub_want()  # nudge a fresh map
        assert wait_until(
            lambda: client.osdmap.epoch >=
            cluster.leader().osdmon.osdmap.epoch, timeout=10)
        # degraded read reconstructs from the survivors
        deadline = time.monotonic() + 20
        data = None
        while time.monotonic() < deadline:
            try:
                data = ioctx.read("degobj")
                if data == payload:
                    break
            except Exception:
                time.sleep(0.2)
        assert data == payload
        # bring it back for the remaining tests
        cluster.revive_osd(victim)
        assert wait_until(
            lambda: cluster.leader().osdmon.osdmap.is_up(victim),
            timeout=10)

    def test_recovery_restores_redundancy(self, ctx):
        cluster, client, ioctx, pool_id = ctx
        payload = b"V" * 16000
        ioctx.write_full("recobj", payload)
        m = client.osdmap
        pool = m.pools[pool_id]
        pgid = pool.raw_pg_to_pg(m.object_to_pg(pool_id, "recobj"))
        _, _, acting, _ = m.pg_to_up_acting_osds(pgid)
        victim = acting[1]
        cluster.stop_osd(victim)
        assert wait_until(
            lambda: cluster.leader().osdmon.osdmap.is_out(victim),
            timeout=15), "victim never marked out"
        # after out, CRUSH remaps the shard to a spare osd; recovery
        # must reconstruct the lost shard there
        def shard_recovered():
            mm = cluster.leader().osdmon.osdmap
            _, _, new_acting, _ = mm.pg_to_up_acting_osds(pgid)
            if any(o == CRUSH_ITEM_NONE for o in new_acting):
                return False
            if victim in new_acting:
                return False
            for shard, osd_id in enumerate(new_acting):
                osd = cluster.osds.get(osd_id)
                if osd is None:
                    return False
                try:
                    data = osd.store.read(("pg", str(pgid), shard),
                                          "recobj")
                except KeyError:
                    return False
                if not data:
                    return False
            return True
        assert wait_until(shard_recovered, timeout=25), \
            "lost shard never reconstructed"
        assert ioctx.read("recobj") == payload
        cluster.revive_osd(victim)
        res, _, _ = client.mon_command({"prefix": "osd in",
                                       "id": victim})
        assert res == 0


class TestECPoolJaxTpuPlugin:
    """The north-star plugin serving a real (mini) cluster."""

    def test_jax_tpu_pool_roundtrip(self):
        # pre-warm the XLA compile outside the cluster: the first encode
        # otherwise stalls an OSD op thread past the (FAST) heartbeat
        # grace and the mon marks the OSD down mid-test
        from ceph_tpu import registry
        from ceph_tpu.osd import ec_util
        codec = registry.factory(
            "jax_tpu", {"technique": "reed_sol_van", "k": "2", "m": "1"})
        # warm the exact shape the in-cluster write hits (jit programs
        # are shape-specialized): 65536 B over stripe_width 8192 = batch 8
        sinfo = ec_util.StripeInfo(2, 8192)
        ec_util.encode(sinfo, codec, b"\0" * 65536)
        cluster = MiniCluster(num_mons=1, num_osds=4,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            pool_id = cluster.create_ec_pool(
                client, "tpudata",
                {"plugin": "jax_tpu", "technique": "reed_sol_van",
                 "k": "2", "m": "1",
                 "crush-failure-domain": "host"}, pg_num=4)
            assert cluster.wait_clean(pool_id)
            ioctx = client.open_ioctx("tpudata")
            rng = np.random.default_rng(11)
            payload = rng.integers(0, 256, size=65536,
                                   dtype=np.uint8).tobytes()
            ioctx.write_full("tobj", payload)
            assert ioctx.read("tobj") == payload
        finally:
            cluster.stop()
