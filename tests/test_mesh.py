"""Multi-device mesh tests on the suite's virtual 8-device CPU platform.

The compute-plane sharding story in-suite (the driver's external
dryrun_multichip is a second check, no longer the only one): sharded
encode/decode must be bit-equal to the single-device path across mesh
shapes, reductions ride psum, and the bulk CRUSH sweep partitions over
the mesh while staying equal to the scalar oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.parallel import mesh as pmesh

K, M, W = 4, 2, 8


@pytest.fixture(scope="module")
def codec():
    return registry.factory("jax_tpu", {"technique": "reed_sol_van",
                                        "k": str(K), "m": str(M),
                                        "w": str(W)})


@pytest.fixture(scope="module")
def payload():
    rng = np.random.default_rng(42)
    # B=8 divides every stripe-axis size; N=4096 divides every block size
    return rng.integers(0, 256, size=(8, K, 4096), dtype=np.uint8)


def test_eight_virtual_devices():
    import jax
    assert len(jax.devices()) == 8
    assert jax.devices()[0].platform == "cpu"


def test_sharded_encode_bit_equal_full_mesh(codec, payload):
    m = pmesh.make_mesh(8)                      # 2 x 4 (stripe, block)
    single = np.asarray(codec.encode_batch(payload))
    sharded = np.asarray(pmesh.encode_sharded(codec, payload, m))
    assert np.array_equal(single, sharded)


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_encode_bit_equal_across_mesh_shapes(codec, payload,
                                                     n_devices):
    m = pmesh.make_mesh(n_devices)
    single = np.asarray(codec.encode_batch(payload))
    sharded = np.asarray(pmesh.encode_sharded(codec, payload, m))
    assert np.array_equal(single, sharded)


def test_sharded_encode_is_actually_distributed(codec, payload):
    m = pmesh.make_mesh(8)
    out = pmesh.encode_sharded(codec, payload, m)
    # the parity must live sharded across all 8 devices, not replicated
    assert len(out.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8 // 2, M, 4096 // 4)}


def test_sharded_decode_bit_equal(codec, payload):
    m = pmesh.make_mesh(8)
    parity = np.asarray(codec.encode_batch(payload))
    full = np.concatenate([payload, parity], axis=1)
    for avail in [(0, 1, 2, 3), (1, 2, 4, 5), (0, 2, 3, 5)]:
        chunks = full[:, list(avail), :]
        sharded = np.asarray(pmesh.decode_sharded(codec, avail, chunks, m))
        single = np.asarray(codec.decode_batch(avail, chunks))
        assert np.array_equal(sharded, single), avail
        assert np.array_equal(sharded, full), avail


def test_psum_reduction_over_mesh(codec, payload):
    """A cross-shard reduction (per-chunk byte checksums, the deep-scrub
    shape) rides psum over the mesh and matches numpy."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = pmesh.make_mesh(8)
    parity = pmesh.encode_sharded(codec, payload, m)

    @jax.jit
    def chunk_sums(x):
        def local(block):
            s = jnp.sum(block.astype(jnp.int64), axis=(0, 2))
            return jax.lax.psum(jax.lax.psum(s, "block"), "stripe")
        return shard_map(
            local, mesh=m,
            in_specs=P("stripe", None, "block"),
            out_specs=P())(x)

    got = np.asarray(chunk_sums(parity))
    want = np.asarray(parity).astype(np.int64).sum(axis=(0, 2))
    assert np.array_equal(got, want)


def test_mesh_sharded_bulk_crush_equals_scalar_oracle():
    """The bulk PG->OSD sweep partitioned across the mesh: every row
    equal to the scalar interpreter (which is itself differential-tested
    against the compiled reference C)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ceph_tpu.crush import map as cmap_mod, mapper_ref
    from ceph_tpu.crush.batched import batched_do_rule
    from ceph_tpu.crush.map import CrushMap, Rule

    rng = np.random.default_rng(9)
    hosts, per = 6, 4
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1, "root": 2}
    host_ids, host_w = [], []
    for h in range(hosts):
        items = [h * per + i for i in range(per)]
        w = [int(weights[i]) for i in items]
        host_ids.append(m.add_bucket("straw2", 1, items, w, id=-2 - h))
        host_w.append(sum(w))
    m.add_bucket("straw2", 2, host_ids, host_w, id=-1, name="default")
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_INDEP, 5, 1),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[2] = 0
    mesh = pmesh.make_mesh(8, axis_names=("pg", "unused"))
    flat = jax.sharding.Mesh(
        np.array(jax.devices()).reshape(8), ("pg",))
    xs = np.arange(256)
    got = batched_do_rule(
        m, 0, xs, 5, reweight,
        xs_sharding=NamedSharding(flat, P("pg")))
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 5, list(reweight))
        assert list(got[x]) == ref, x
