"""Multi-device mesh tests on the suite's virtual 8-device CPU platform.

The compute-plane sharding story in-suite (the driver's external
dryrun_multichip is a second check, no longer the only one): sharded
encode/decode must be bit-equal to the single-device path across mesh
shapes, reductions ride psum, and the bulk CRUSH sweep partitions over
the mesh while staying equal to the scalar oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu import registry
from ceph_tpu.parallel import mesh as pmesh

K, M, W = 4, 2, 8


@pytest.fixture(scope="module")
def codec():
    return registry.factory("jax_tpu", {"technique": "reed_sol_van",
                                        "k": str(K), "m": str(M),
                                        "w": str(W)})


@pytest.fixture(scope="module")
def payload():
    rng = np.random.default_rng(42)
    # B=8 divides every stripe-axis size; N=4096 divides every block size
    return rng.integers(0, 256, size=(8, K, 4096), dtype=np.uint8)


def test_eight_virtual_devices():
    import jax
    assert len(jax.devices()) == 8
    assert jax.devices()[0].platform == "cpu"


def test_sharded_encode_bit_equal_full_mesh(codec, payload):
    m = pmesh.make_mesh(8)                      # 2 x 4 (stripe, block)
    single = np.asarray(codec.encode_batch(payload))
    sharded = np.asarray(pmesh.encode_sharded(codec, payload, m))
    assert np.array_equal(single, sharded)


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_encode_bit_equal_across_mesh_shapes(codec, payload,
                                                     n_devices):
    m = pmesh.make_mesh(n_devices)
    single = np.asarray(codec.encode_batch(payload))
    sharded = np.asarray(pmesh.encode_sharded(codec, payload, m))
    assert np.array_equal(single, sharded)


def test_sharded_encode_is_actually_distributed(codec, payload):
    m = pmesh.make_mesh(8)
    out = pmesh.encode_sharded(codec, payload, m)
    # the parity must live sharded across all 8 devices, not replicated
    assert len(out.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(8 // 2, M, 4096 // 4)}


def test_sharded_decode_bit_equal(codec, payload):
    m = pmesh.make_mesh(8)
    parity = np.asarray(codec.encode_batch(payload))
    full = np.concatenate([payload, parity], axis=1)
    for avail in [(0, 1, 2, 3), (1, 2, 4, 5), (0, 2, 3, 5)]:
        chunks = full[:, list(avail), :]
        sharded = np.asarray(pmesh.decode_sharded(codec, avail, chunks, m))
        single = np.asarray(codec.decode_batch(avail, chunks))
        assert np.array_equal(sharded, single), avail
        assert np.array_equal(sharded, full), avail


def test_psum_reduction_over_mesh(codec, payload):
    """A cross-shard reduction (per-chunk byte checksums, the deep-scrub
    shape) rides psum over the mesh and matches numpy."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = pmesh.make_mesh(8)
    parity = pmesh.encode_sharded(codec, payload, m)

    @jax.jit
    def chunk_sums(x):
        def local(block):
            s = jnp.sum(block.astype(jnp.int64), axis=(0, 2))
            return jax.lax.psum(jax.lax.psum(s, "block"), "stripe")
        return shard_map(
            local, mesh=m,
            in_specs=P("stripe", None, "block"),
            out_specs=P())(x)

    got = np.asarray(chunk_sums(parity))
    want = np.asarray(parity).astype(np.int64).sum(axis=(0, 2))
    assert np.array_equal(got, want)


def test_mesh_sharded_bulk_crush_equals_scalar_oracle():
    """The bulk PG->OSD sweep partitioned across the mesh: every row
    equal to the scalar interpreter (which is itself differential-tested
    against the compiled reference C)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ceph_tpu.crush import map as cmap_mod, mapper_ref
    from ceph_tpu.crush.batched import batched_do_rule
    from ceph_tpu.crush.map import CrushMap, Rule

    rng = np.random.default_rng(9)
    hosts, per = 6, 4
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1, "root": 2}
    host_ids, host_w = [], []
    for h in range(hosts):
        items = [h * per + i for i in range(per)]
        w = [int(weights[i]) for i in items]
        host_ids.append(m.add_bucket("straw2", 1, items, w, id=-2 - h))
        host_w.append(sum(w))
    m.add_bucket("straw2", 2, host_ids, host_w, id=-1, name="default")
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_INDEP, 5, 1),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[2] = 0
    mesh = pmesh.make_mesh(8, axis_names=("pg", "unused"))
    flat = jax.sharding.Mesh(
        np.array(jax.devices()).reshape(8), ("pg",))
    xs = np.arange(256)
    got = batched_do_rule(
        m, 0, xs, 5, reweight,
        xs_sharding=NamedSharding(flat, P("pg")))
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 5, list(reweight))
        assert list(got[x]) == ref, x


# ---------------------------------------------------------------------------
# mesh-native cluster: placement, pinned pipelines, balancer, recovery


def _toy_osdmap(num_osds=6, pg_num=32):
    from ceph_tpu.crush.map import CrushMap, weight_fixed
    from ceph_tpu.osd.osd_map import OSDMap, PGPool
    m = OSDMap()
    m.set_max_osd(num_osds)
    cm = CrushMap()
    cm.type_names.update({"osd": 0, "host": 1, "root": 2})
    hosts = num_osds // 2
    for h in range(hosts):
        cm.add_bucket("straw2", 1, [2 * h, 2 * h + 1],
                      [weight_fixed(1.0)] * 2, name="host%d" % h)
    cm.add_bucket("straw2", 2, [-1 - h for h in range(hosts)],
                  [weight_fixed(2.0)] * hosts, name="default")
    cm.add_simple_rule("r", "default")
    m.crush = cm
    for o in range(num_osds):
        m.osd_exists[o] = True
        m.osd_up[o] = True
        m.osd_weight[o] = 0x10000
    m.pools[1] = PGPool(1, "p", size=3, pg_num=pg_num, crush_rule=0)
    m.pools[2] = PGPool(2, "q", size=2, pg_num=pg_num // 2,
                        crush_rule=0)
    return m


def test_placement_registry_round_robin():
    """One OSD per chip with zero per-daemon conf: the default
    osd_device_index=-1 round-robins by osd id over the fake mesh."""
    import jax

    from ceph_tpu.parallel.placement import (DevicePlacement,
                                             device_label)
    reg = DevicePlacement()
    devs = jax.devices()
    for osd in range(10):
        dev = reg.resolve(osd)
        assert dev is devs[osd % len(devs)]
    # explicit index wins (modulo the device count)
    assert reg.resolve(99, device_index=3) is devs[3]
    doc = reg.assignments()
    assert doc["num_devices"] == len(devs)
    assert doc["osds"]["0"]["device"] == device_label(devs[0])
    assert doc["osds"]["9"]["device"] == device_label(devs[9 % 8])


def test_pinned_dispatchers_concurrent_disjoint_buffers(codec, payload):
    """Two dispatchers pinned to distinct devices drive concurrently:
    results bit-equal to the host reference, and each pipeline's
    device buffers (the HBM-tier residents it adopts) live ONLY on
    its home device — no shared default-device staging."""
    import threading

    import jax

    from ceph_tpu.osd.hbm_tier import HbmChunkTier
    from ceph_tpu.osd.tpu_dispatch import TpuDispatcher

    dev_a, dev_b = jax.devices()[2], jax.devices()[5]
    ref = np.asarray(codec.encode_batch(payload))
    results = {}

    def drive(name, dev):
        disp = TpuDispatcher(max_delay=0.001, device=dev)
        tier = HbmChunkTier(capacity_objects=8, device=dev)
        try:
            for i in range(4):
                out = np.asarray(disp.encode(
                    codec, payload,
                    resident=(tier, ("pg", "%s-%d" % (name, i)))))
            results[name] = (out, tier)
        finally:
            disp.shutdown()

    t_a = threading.Thread(target=drive, args=("a", dev_a))
    t_b = threading.Thread(target=drive, args=("b", dev_b))
    t_a.start()
    t_b.start()
    t_a.join()
    t_b.join()
    out_a, tier_a = results["a"]
    out_b, tier_b = results["b"]
    assert np.array_equal(out_a, ref)
    assert np.array_equal(out_b, ref)
    # residency is disjoint per home device
    devs_a = {d for batch, _row in tier_a._objs.values()
              for d in batch.arr.devices()}
    devs_b = {d for batch, _row in tier_b._objs.values()
              for d in batch.arr.devices()}
    assert devs_a == {dev_a}, devs_a
    assert devs_b == {dev_b}, devs_b


def test_mesh_balancer_sweep_matches_native_exactly():
    """The sharded all-PG sweep (direction D / carried item 5) must be
    bit-identical to the native mapper — same PG -> OSD mapping for
    every PG of every pool, straight through OSDMapMapping.update."""
    from ceph_tpu.osd.balancer import _sweep
    from ceph_tpu.osd.osd_map import OSDMapMapping

    m = _toy_osdmap()
    native = _sweep(m, None, use_device=False)
    mesh = _sweep(m, None, use_device=False, use_mesh=True)
    assert mesh == native
    # and the full mapping document (up/acting/primaries) agrees too
    a, b = OSDMapMapping(), OSDMapMapping()
    a.update(m, batched=False)
    b.update(m, batched=True, mesh=True)
    assert a.by_pg == b.by_pg


def test_balancer_module_measures_mesh_backend():
    """pick_backend probes all three backends and records medians the
    operator can read back (`balancer status`)."""
    import types

    from ceph_tpu.mgr.modules import BalancerModule

    bal = BalancerModule(types.SimpleNamespace(metrics=None))
    bal.min_speed_samples = 1
    m = _toy_osdmap(pg_num=16)
    best = bal.pick_backend(m)
    assert best in ("native", "device", "mesh")
    for backend in ("native", "device", "mesh"):
        assert len(bal.sweep_samples[backend]) == 1
    meds = bal.sweep_medians()
    assert set(meds) == {"native", "device", "mesh"}


def test_cross_chip_recovery_byte_equality(codec):
    """recover_object's survivor fallback shape: reconstruct one
    missing shard via the mesh (sharded survivors + psum checksum),
    byte-identical to the host decode."""
    from ceph_tpu.osd import ec_util

    sinfo = ec_util.StripeInfo(K, K * 256)
    rng = np.random.default_rng(7)
    payload = rng.integers(0, 256, size=8 * K * 256,
                           dtype=np.uint8).tobytes()
    shards = ec_util.encode(sinfo, codec, payload)
    for target, lost2 in ((5, 2), (0, 4), (3, 1)):
        survivors = {s: v for s, v in shards.items()
                     if s not in (target, lost2)}
        use = tuple(sorted(survivors))[:K]
        survivors = {s: survivors[s] for s in use}
        got = ec_util.recover_cross_chip(sinfo, codec, survivors,
                                         target)
        want = np.asarray(
            ec_util.decode(sinfo, codec, survivors,
                           want={target})[target],
            dtype=np.uint8).tobytes()
        assert got == want, (target, lost2)


def test_cross_chip_recovery_checksum_trips_on_corruption(codec):
    """The psum checksum over the mesh must trip when the survivor
    bytes are corrupted after the host reference sum was taken —
    the device-resident inputs no longer match what was received."""
    from ceph_tpu.parallel.mesh import MeshChecksumError, \
        recover_sharded

    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(8, K, 256), dtype=np.uint8)
    parity = np.asarray(codec.encode_batch(data))
    full = np.concatenate([data, parity], axis=1)
    avail = (0, 1, 3, 4)
    chunks = full[:, list(avail), :].copy()
    expected = int(chunks.astype(np.uint64).sum()) % (1 << 32)
    # clean run reconstructs row 2 exactly
    out = recover_sharded(codec, avail, chunks, 2,
                          expected_sum=expected)
    assert np.array_equal(out, full[:, 2, :])
    # inject corruption AFTER the expected checksum was computed
    chunks[3, 1, 17] ^= 0xFF
    with pytest.raises(MeshChecksumError):
        recover_sharded(codec, avail, chunks, 2,
                        expected_sum=expected)


def test_straggler_keeps_other_devices_within_spread(codec, payload):
    """Wedging ONE pinned pipeline's h2d hop must not stall the other
    devices (no cross-pipeline serialization).

    Deterministic formulation: the straggler's h2d blocks on an Event
    instead of a sleep, and the invariant is ORDERING — the three
    healthy pipelines' encodes complete while pipeline 3 is provably
    still stuck inside its h2d — so a loaded box slows the test down
    but can never flip its verdict (the old wall-clock-rate spread
    comparison flaked under scheduler noise)."""
    import threading

    import jax

    from ceph_tpu.osd.tpu_dispatch import TpuDispatcher

    devs = jax.devices()[:4]
    disps = [TpuDispatcher(max_delay=0.008, device=d) for d in devs]
    gate = threading.Event()
    entered = threading.Event()
    orig_h2d = disps[3]._devops.h2d

    def wedged_h2d(host):
        entered.set()
        assert gate.wait(60), "straggler gate never released"
        return orig_h2d(host)

    results: dict = {}

    def drive(i):
        results[i] = np.asarray(disps[i].encode(codec, payload))

    try:
        expect = np.asarray(disps[0].encode(codec, payload))  # warm
        for d in disps[1:]:
            np.asarray(d.encode(codec, payload))
        disps[3]._devops.h2d = wedged_h2d
        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(len(disps))]
        for t in threads:
            t.start()
        try:
            # the straggler is INSIDE its h2d hop...
            assert entered.wait(60), "straggler never reached h2d"
            # ...and the healthy pipelines complete while it is stuck
            for i in (0, 1, 2):
                threads[i].join(timeout=60)
                assert not threads[i].is_alive(), \
                    "pipeline %d stalled behind the straggler" % i
                assert np.array_equal(results[i], expect)
            assert threads[3].is_alive(), \
                "straggler finished while its h2d was gated"
        finally:
            gate.set()
        threads[3].join(timeout=60)
        assert not threads[3].is_alive()
        assert np.array_equal(results[3], expect)
    finally:
        gate.set()
        disps[3]._devops.h2d = orig_h2d
        for d in disps:
            d.shutdown()


# ---------------------------------------------------------------------------
# rateless work-stealing dispatch (parallel/rateless.py, direction J)
# ---------------------------------------------------------------------------

class _FakeClock:
    """Injectable monotonic clock: every deadline / blacklist decision
    in RatelessDispatcher reads this, so tests advance logical time
    explicitly instead of sleeping (PR-13 deterministic-clock
    precedent — wall-clock scheduling noise can slow a test down but
    never flip its verdict)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def rateless_pair():
    """(dispatcher, injector, fake clock) over 2 devices, torn down."""
    import jax

    from ceph_tpu.parallel.rateless import (DeviceFaultSet,
                                            RatelessDispatcher)
    clk = _FakeClock()
    inj = DeviceFaultSet(seed=3)
    rl = RatelessDispatcher(devices=jax.devices()[:2], clock=clk,
                            injector=inj, name="test-rl")
    yield rl, inj, clk
    rl.shutdown()


def _spin(check, timeout=30.0):
    """Poll a timing-independent predicate: generous wall deadline,
    verdict decided by the predicate alone."""
    import time as _time
    end = _time.monotonic() + timeout
    while _time.monotonic() < end:
        if check():
            return True
        _time.sleep(0.005)
    return check()


class TestRatelessWorkStealing:
    def test_bit_identical_to_fixed_shard_oracle_under_stalls(
            self, codec, payload):
        """Random per-device stalls reshuffle WHICH chip runs each
        micro-batch; the reassembled result must stay bit-identical to
        the oracle, and idle devices must actually steal (a stolen
        micro-batch = completed off its fixed-shard home)."""
        import jax

        from ceph_tpu.parallel.rateless import (DeviceFaultSet,
                                                RatelessDispatcher)
        rng = np.random.default_rng(17)
        inj = DeviceFaultSet(seed=17)
        rl = RatelessDispatcher(devices=jax.devices()[:4],
                                injector=inj, name="steal-rl")
        try:
            want = np.asarray(codec.encode_batch(payload))
            for trial in range(3):
                inj.clear_all()
                for idx in range(4):
                    if rng.random() < 0.5:
                        inj.stall_ms(idx, float(rng.integers(1, 15)))
                got = np.asarray(rl.encode(codec, payload))
                assert np.array_equal(got, want), trial
            assert rl.status()["stolen_total"] > 0
        finally:
            inj.clear_all()
            rl.shutdown()

    def test_lt_coded_decode_bit_identical(self, codec, payload):
        """LT-coded dispatch: coded micro-batches are XORs of seeded
        source subsets; the peeling decoder must reassemble the exact
        plain result from whichever subset lands first."""
        import jax

        from ceph_tpu.parallel.rateless import RatelessDispatcher
        rl = RatelessDispatcher(devices=jax.devices()[:4],
                                name="lt-rl")
        try:
            parity = np.asarray(codec.encode_batch(payload))
            full = np.concatenate([payload, parity], axis=1)
            avail = (0, 2, 3, 5)
            chunks = full[:, list(avail), :]
            want = np.asarray(codec.decode_batch(avail, chunks))
            for seed in (0, 1, 2):
                got = np.asarray(rl.decode(codec, avail, chunks,
                                           lt=True, seed=seed))
                assert np.array_equal(got, want), seed
        finally:
            rl.shutdown()

    def test_queue_path_equals_mesh_do_rule_oracle(self):
        """crush.mesh_do_rule adopts the work queue when no explicit
        mesh is passed: the bulk sweep must equal the scalar oracle."""
        from ceph_tpu.crush import map as cmap_mod, mapper_ref
        from ceph_tpu.crush.batched import mesh_do_rule
        from ceph_tpu.crush.map import CrushMap, Rule
        from ceph_tpu.parallel import rateless

        cm = CrushMap()
        cm.type_names = {"osd": 0, "host": 1, "root": 2}
        host_ids, host_w = [], []
        for h in range(3):
            items = [h * 2 + i for i in range(2)]
            w = [0x10000] * 2
            host_ids.append(cm.add_bucket("straw2", 1, items, w,
                                          id=-2 - h))
            host_w.append(sum(w))
        cm.add_bucket("straw2", 2, host_ids, host_w, id=-1,
                      name="default")
        cm.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                                (cmap_mod.RULE_CHOOSELEAF_INDEP, 3, 1),
                                (cmap_mod.RULE_EMIT,)]))
        weight = np.full(6, 0x10000, dtype=np.int64)
        xs = list(range(48))
        assert rateless.get_dispatcher() is not None, \
            "queue dispatcher unavailable on the 8-device suite"
        got = mesh_do_rule(cm, 0, xs, 3, weight)
        for seed in xs:
            assert list(got[seed]) == mapper_ref.crush_do_rule(
                cm, 0, seed, 3, list(weight)), seed


class TestSpeculativeRedispatch:
    def test_first_result_wins_and_duplicate_discarded(
            self, codec, payload, rateless_pair):
        """Wedge one chip past its (fake-clock) deadline mid-encode:
        the overdue micro-batch is speculatively re-dispatched, the
        healthy chip's copy seals the job, and the straggler's late
        answer is discarded as a duplicate — result bit-identical."""
        import threading
        import time as _time

        from ceph_tpu.common.profiler import PROFILER
        rl, inj, clk = rateless_pair
        want = np.asarray(codec.encode_batch(payload))
        # prime the latency EWMA (deadline stays inf with no sample)
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        prev_enabled, PROFILER.enabled = PROFILER.enabled, True
        inj.stall_ms(0, 400.0)
        stop = threading.Event()

        def tick():
            while not stop.is_set():
                clk.advance(0.05)
                _time.sleep(0.002)

        t = threading.Thread(target=tick, daemon=True)
        t.start()
        try:
            got = np.asarray(rl.encode(codec, payload))
            assert np.array_equal(got, want)
            st = rl.status()
            assert st["redispatch_total"] >= 1
            # the wedged chip's late answers surface as discarded
            # duplicates once it wakes (first-result-wins by seq)
            assert _spin(
                lambda: rl.status()["duplicate_total"] >= 1), \
                rl.status()
            # the duplicated buffers went through the device-memory
            # ledger and were released when their seq sealed
            mem = PROFILER.mem_dump().get("speculative_buffers")
            assert mem is not None and mem["high_watermark"] > 0
            assert _spin(lambda: PROFILER.mem_dump()
                         ["speculative_buffers"]["bytes"] == 0)
        finally:
            stop.set()
            t.join()
            inj.clear_all()
            PROFILER.enabled = prev_enabled


class TestBlacklistProbation:
    def test_strikeout_blacklists_then_probation_readmits(
            self, codec, payload, rateless_pair):
        """Three erroring pulls blacklist the chip; the encode still
        completes on the survivor; after the (fake-clock) backoff one
        canary micro-batch re-admits it to healthy."""
        rl, inj, clk = rateless_pair
        want = np.asarray(codec.encode_batch(payload))
        inj.fail_next(0, 3)
        # the 3 strikes normally land inside one encode (the failing
        # pulls are instant); extra rounds only guard the rare
        # schedule where the survivor drains the queue first
        for _ in range(5):
            assert np.array_equal(
                np.asarray(rl.encode(codec, payload)), want)
            if rl.health[0].state == "blacklisted":
                break
        assert _spin(lambda: rl.health[0].state == "blacklisted")
        assert rl.degraded() == 1
        assert rl.health[0].errors == 3
        # backoff not yet expired: the chip must NOT take work
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        assert rl.health[0].state == "blacklisted"
        # expire the backoff: the next job hands it ONE canary, the
        # canary lands clean (fake clock: dt 0 <= deadline), re-admit
        clk.advance(60.0)
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        assert _spin(lambda: rl.health[0].state == "healthy")
        assert rl.degraded() == 0
        assert rl.health[0].strikes == 0

    def test_failed_canary_doubles_backoff(self, codec, payload,
                                           rateless_pair):
        """A canary that errors goes straight back to the blacklist
        with a DOUBLED backoff (exponential probation)."""
        rl, inj, clk = rateless_pair
        want = np.asarray(codec.encode_batch(payload))
        inj.fail_next(0, 4)          # 3 strikes + 1 failed canary
        for _ in range(5):
            assert np.array_equal(
                np.asarray(rl.encode(codec, payload)), want)
            if rl.health[0].state == "blacklisted":
                break
        assert _spin(lambda: rl.health[0].state == "blacklisted")
        first_until = rl.health[0].blacklist_until
        clk.advance(60.0)
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        assert _spin(lambda: rl.health[0].blacklist_total == 2)
        assert rl.health[0].state == "blacklisted"
        assert rl.health[0].backoffs == 2
        # doubled: the second backoff window is twice the first
        assert (rl.health[0].blacklist_until - clk()) \
            > (first_until - 0.0) * 1.5
        # and a clean canary after the doubled backoff still re-admits
        clk.advance(60.0)
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        assert _spin(lambda: rl.health[0].state == "healthy")


class TestDeadChipDrain:
    def test_mid_batch_kill_drains_and_completes_on_survivor(
            self, codec, payload, rateless_pair):
        """Kill a chip WHILE it holds an in-flight micro-batch: the
        item drains back to the queue (zero lost), the job seals on
        the survivor bit-identically, and the mesh reports n-1."""
        import threading

        rl, inj, clk = rateless_pair
        want = np.asarray(codec.encode_batch(payload))
        # wedge chip 0 so it provably holds work when the kill lands
        inj.stall_ms(0, 250.0)
        got_box: dict = {}

        def drive():
            got_box["out"] = np.asarray(rl.encode(codec, payload))

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        assert _spin(lambda: rl.health[0].inflight >= 1), \
            "chip 0 never pulled a micro-batch"
        inj.kill(0)
        t.join(timeout=60)
        assert not t.is_alive(), "encode hung after mid-batch kill"
        assert np.array_equal(got_box["out"], want)
        assert _spin(lambda: rl.degraded() == 1)
        assert rl.health[0].state == "blacklisted"
        # revive: the chip re-enters via probation, not straight in
        inj.clear_all()
        clk.advance(60.0)
        assert np.array_equal(
            np.asarray(rl.encode(codec, payload)), want)
        assert _spin(lambda: rl.health[0].state == "healthy")
        assert rl.degraded() == 0

    def test_all_chips_killed_falls_back_to_host(self, codec, payload,
                                                 rateless_pair):
        """Degenerate survival: with EVERY chip killed the caller
        thread runs the remaining micro-batches inline — degraded to
        the host, never failed, still bit-identical."""
        rl, inj, clk = rateless_pair
        want = np.asarray(codec.encode_batch(payload))
        inj.kill(0)
        inj.kill(1)
        got = np.asarray(rl.encode(codec, payload))
        assert np.array_equal(got, want)
        inj.clear_all()


class TestCoalesceWaitEwma:
    def test_take_group_wait_tracks_latency_ewma(self):
        """The dispatcher's straggler-wait satellite: _coalesce_wait
        follows the rolling dispatch-latency EWMA instead of pinning
        to the configured max_delay, floored at max_delay/8."""
        from ceph_tpu.osd.tpu_dispatch import TpuDispatcher
        d = TpuDispatcher(max_delay=0.016)
        try:
            # no samples yet: fall back to the configured window
            assert d._coalesce_wait() == d.max_delay
            # fast completions shrink the window (half the EWMA)...
            for _ in range(64):
                d._note_dispatch_wall(0.008)
            assert abs(d._coalesce_wait() - 0.004) < 4e-4
            # ...but never below max_delay/8
            for _ in range(64):
                d._note_dispatch_wall(1e-5)
            assert d._coalesce_wait() == d.max_delay / 8.0
            # slow completions are capped at the configured window
            for _ in range(64):
                d._note_dispatch_wall(1.0)
            assert d._coalesce_wait() == d.max_delay
            st = d.dispatch_status()
            assert st["lat_ewma_ms"] > 0
            assert st["coalesce_wait_ms"] == d.max_delay * 1e3
        finally:
            d.shutdown()


# ---------------------------------------------------------------------------
# DEVICE_DEGRADED health + observability + chaos (cluster level)
# ---------------------------------------------------------------------------

_FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
         "mon_osd_down_out_interval": 1.0,
         "paxos_propose_interval": 0.02}


def _health_checks(client):
    res, _, data = client.mon_command({"prefix": "health"})
    assert res == 0
    return data["checks"]


class TestDeviceDegradedHealth:
    def test_blacklisted_chip_raises_and_clears_device_degraded(
            self, codec, payload):
        """An injector-killed chip blacklists out of the mesh queue;
        the OSD's MPGStats report carries the count, the mon raises
        DEVICE_DEGRADED, and the probation re-admit after revival
        clears it.  The mesh health also shows up in `mesh status`
        asok and in the mgr's Prometheus exposition."""
        import jax

        from ceph_tpu.mgr import MgrDaemon, PrometheusModule
        from ceph_tpu.parallel import rateless
        from ceph_tpu.parallel.rateless import (DeviceFaultSet,
                                                RatelessDispatcher)

        from .cluster_util import MiniCluster, wait_until

        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=_FAST).start()
        inj = DeviceFaultSet(seed=5)
        rl = RatelessDispatcher(devices=jax.devices()[:2],
                                injector=inj, name="health-rl")
        old = rateless.get_dispatcher(create=False)
        rateless.set_dispatcher(rl)
        mgr = MgrDaemon(cluster.monmap)
        mgr.init()
        for osd in cluster.osds.values():
            osd.mgr_addr = mgr.addr
        try:
            client = cluster.client()
            inj.kill(0)
            assert wait_until(lambda: rl.degraded() >= 1, timeout=10)
            assert wait_until(
                lambda: "DEVICE_DEGRADED" in _health_checks(client),
                timeout=20)
            check = _health_checks(client)["DEVICE_DEGRADED"]
            assert check["severity"] == "warning"
            assert any("blacklisted" in d for d in check["detail"])
            # mesh status asok carries the per-device health table
            doc = cluster.osds[0]._mesh_status()["rateless"]
            states = {row["device"]: row["state"]
                      for row in doc["devices"]}
            assert "blacklisted" in states.values()
            assert {"ewma_ms", "inflight", "stolen", "redispatched",
                    "blacklisted", "probation"} <= set(
                        doc["devices"][0])
            # ...and the mgr exports the device-health series
            prom = mgr.register_module(PrometheusModule)
            assert wait_until(
                lambda: "ceph_tpu_device_health" in prom.render(),
                timeout=15)
            text = prom.render()
            assert "ceph_tpu_mesh_blacklist" in text
            assert "ceph_tpu_mesh_redispatch_total" in text
            # revive: the canary path re-admits the chip, the osd
            # re-reports zero, the mon clears the check
            inj.revive(0)

            def readmitted():
                np.asarray(rl.encode(codec, payload[:2]))
                return rl.degraded() == 0
            assert wait_until(readmitted, timeout=20)
            assert wait_until(
                lambda: "DEVICE_DEGRADED"
                not in _health_checks(client), timeout=20)
        finally:
            rateless.set_dispatcher(old)
            rl.shutdown()
            mgr.shutdown()
            cluster.stop()


@pytest.mark.slow
class TestChipKillChaos:
    def test_chip_chaos_under_io_reaches_health_ok(self, codec,
                                                   payload):
        """Long leg: the thrasher kills/revives mesh chips while
        client IO and rateless encodes run; when the dust settles
        every encode stayed bit-identical, the devices are all
        re-admitted, and the cluster reports HEALTH_OK."""
        import jax

        from ceph_tpu.parallel import rateless
        from ceph_tpu.parallel.rateless import (DEVICE_FAULTS,
                                                RatelessDispatcher)

        from .cluster_util import MiniCluster, wait_until
        from .thrasher import Thrasher

        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=_FAST).start()
        rl = RatelessDispatcher(devices=jax.devices()[:4],
                                injector=DEVICE_FAULTS,
                                name="chaos-rl")
        old = rateless.get_dispatcher(create=False)
        rateless.set_dispatcher(rl)
        want = np.asarray(codec.encode_batch(payload))
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "chaos", size=2,
                                           pg_num=4)
            ioctx = client.open_ioctx("chaos")
            thrasher = Thrasher(cluster, seed=11, min_in=3,
                                device_thrash_prob=0.9,
                                interval=0.2)
            thrasher.start()
            try:
                for i in range(30):
                    ioctx.write_full("c%d" % i, b"%d" % i * 64)
                    got = np.asarray(rl.encode(codec, payload))
                    assert np.array_equal(got, want), i
            finally:
                thrasher.stop_and_heal()
            assert thrasher.log, "thrasher never acted"
            assert any(a[0] == "device_kill" for a in thrasher.log), \
                "no chip was ever killed: %s" % (thrasher.log[:8],)
            # every chip re-admits through probation once work flows
            def all_healthy():
                np.asarray(rl.encode(codec, payload[:2]))
                return rl.degraded() == 0
            assert wait_until(all_healthy, timeout=30)

            def healthy():
                _, _, data = client.mon_command({"prefix": "health"})
                return bool(data) and data.get("status") == "HEALTH_OK"
            assert wait_until(healthy, timeout=40), \
                client.mon_command({"prefix": "health"})[1]
            for i in range(30):
                assert ioctx.read("c%d" % i) == b"%d" % i * 64, i
        finally:
            DEVICE_FAULTS.clear_all()
            rateless.set_dispatcher(old)
            rl.shutdown()
            cluster.stop()
