"""Pallas GF kernel: bit-exactness vs the XLA/numpy reference paths.

The kernel itself runs on TPU; here it executes in Pallas interpreter
mode on the CPU test platform, asserting the fused
unpack->MXU-matmul->pack pipeline reproduces ops.xor_mm and
ops.gf_ref byte-for-byte (the BASELINE correctness gate applies to
every backend path).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ceph_tpu.ops import gf, gf_ref, pallas_gf, xor_mm


def make_bitmat(k, m):
    coding = gf.rs_vandermonde_generator(k, m, 8)
    return coding, gf.generator_to_bitmatrix(coding, 8)


@pytest.mark.parametrize("k,m,batch,n", [
    (8, 3, 4, 1024),     # flagship geometry
    (2, 1, 1, 512),      # minimal
    (12, 4, 3, 1536),    # wide
])
def test_matches_xla_path(k, m, batch, n):
    coding, bm = make_bitmat(k, m)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(batch, k, n), dtype=np.uint8)
    ref = np.asarray(xor_mm.matrix_encode(jnp.asarray(bm),
                                          jnp.asarray(data), 8))
    out = np.asarray(pallas_gf.matrix_encode8(
        jnp.asarray(bm), jnp.asarray(data), interpret=True))
    assert np.array_equal(ref, out)


def test_matches_numpy_reference():
    coding, bm = make_bitmat(4, 2)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
    out = np.asarray(pallas_gf.matrix_encode8(
        jnp.asarray(bm), jnp.asarray(data), interpret=True))
    for b in range(2):
        ref = gf_ref.matrix_encode_ref(coding, data[b], 8)
        assert np.array_equal(out[b], ref)


def test_decode_matrix_shape_works():
    """The same kernel serves cached decode bitmatrices
    ([(k+m)*8, k*8], more output rows than a generator)."""
    coding, _ = make_bitmat(4, 2)
    dec = gf.decode_matrix(coding, 4, (0, 2, 3, 5), 8)
    parity = gf.gf_matmul(coding, dec, 8)
    full = np.concatenate([dec, parity], axis=0)
    bm = gf.generator_to_bitmatrix(full, 8)
    rng = np.random.default_rng(3)
    chunks = rng.integers(0, 256, size=(1, 4, 512), dtype=np.uint8)
    ref = np.asarray(xor_mm.matrix_encode(jnp.asarray(bm),
                                          jnp.asarray(chunks), 8))
    out = np.asarray(pallas_gf.matrix_encode8(
        jnp.asarray(bm), jnp.asarray(chunks), interpret=True))
    assert np.array_equal(ref, out)


def test_unaligned_length_rejected():
    _, bm = make_bitmat(2, 1)
    with pytest.raises(AssertionError):
        pallas_gf.matrix_encode8(
            jnp.asarray(bm), jnp.zeros((1, 2, 500), dtype=jnp.uint8),
            interpret=True)


def test_production_dispatch_is_xla_only():
    """The Pallas kernel is retired from production (see pallas_gf's
    postmortem): xor_mm must have no dispatch hook and always run the
    XLA path."""
    assert not hasattr(xor_mm, "_pallas_enabled")
    _, bm = make_bitmat(4, 2)
    data = np.ones((2, 4, 512), dtype=np.uint8)
    out = np.asarray(xor_mm.matrix_encode(jnp.asarray(bm),
                                          jnp.asarray(data), 8))
    assert out.shape == (2, 2, 512)


def test_ragged_tail_pads_through_kernel():
    """N not a multiple of the tile rides the kernel via zero padding
    (zeros are the XOR identity) and stays bit-exact."""
    import numpy as np
    from ceph_tpu.ops import gf, gf_ref, pallas_gf
    rng = np.random.default_rng(11)
    k, m = 4, 2
    gen = gf.rs_vandermonde_generator(k, m, 8)
    bitmat = gf.generator_to_bitmatrix(gen, 8)
    for n in (512 + 128, 1024 + 384, 2048 - 128):
        data = rng.integers(0, 256, size=(2, k, n), dtype=np.uint8)
        import jax.numpy as jnp
        pad = (-n) % pallas_gf._TILE_N
        padded = jnp.pad(jnp.asarray(data), ((0, 0), (0, 0), (0, pad)))
        got = np.asarray(pallas_gf.matrix_encode8(
            jnp.asarray(bitmat), padded, interpret=True))[..., :n]
        want = np.stack([gf_ref.matrix_encode_ref(gen, d, 8)
                         for d in data])
        assert np.array_equal(got, want), n
