"""Test env: force the CPU backend with a virtual 8-device mesh.

Tests never require TPU hardware; sharding logic is validated on a
virtual 8-device CPU platform (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this image pre-imports jax at interpreter startup with the platform
pinned, so JAX_PLATFORMS env alone is not enough — use config.update
before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Lock-order cycle detection rides along for the WHOLE suite (the
# reference runs its qa with lockdep enabled the same way); the daemon
# locks created through common.lockdep.make_rlock become DebugRLocks.
# Violations collect rather than raise; the session-end hook surfaces
# any cycle the workload tests provoked.
from ceph_tpu.common import lockdep  # noqa: E402

lockdep.enable()


def pytest_sessionfinish(session, exitstatus):
    if lockdep.violations:
        print("\nLOCKDEP: %d lock-order violation(s) detected:"
              % len(lockdep.violations))
        for v in lockdep.violations[:3]:
            print(v)


# -- heavy-test gating -------------------------------------------------
# The default run (what CI / the driver executes: `pytest tests/ -x -q`)
# skips tests marked `heavy` — long chaos/thrash scenarios whose value
# is stress coverage, not regression signal — keeping it well under
# 10 minutes. `pytest --heavy` (or CEPH_TPU_HEAVY=1) runs everything.

def pytest_addoption(parser):
    parser.addoption(
        "--heavy", action="store_true", default=False,
        help="also run tests marked 'heavy' (long chaos/thrash runs)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "heavy: long chaos/stress test, skipped by default "
        "(enable with --heavy or CEPH_TPU_HEAVY=1)")


def pytest_collection_modifyitems(config, items):
    import pytest
    if config.getoption("--heavy") or os.environ.get("CEPH_TPU_HEAVY"):
        return
    skip = pytest.mark.skip(
        reason="heavy (run with --heavy or CEPH_TPU_HEAVY=1)")
    for item in items:
        if "heavy" in item.keywords:
            item.add_marker(skip)
