"""Test env: force the CPU backend with a virtual 8-device mesh.

Tests never require TPU hardware; sharding logic is validated on a
virtual 8-device CPU platform (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

This IS the CPU-CI fake-mesh recipe (README "Mesh-native cluster"):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

Under it the whole suite runs mesh-native — MiniCluster assigns
osd_device_index round-robin, so every OSD's dispatcher/HBM tier pins
to its own fake device, exactly the one-OSD-per-chip deployment shape.

Note: this image pre-imports jax at interpreter startup with the platform
pinned, so JAX_PLATFORMS env alone is not enough — use config.update
before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Lock-order cycle detection rides along for the WHOLE suite (the
# reference runs its qa with lockdep enabled the same way); the daemon
# locks created through common.lockdep.make_rlock become DebugRLocks.
# Violations collect rather than raise; the session-end hook surfaces
# any cycle the workload tests provoked.
from ceph_tpu.common import lockdep  # noqa: E402

lockdep.enable()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _quiesce_device_profiler():
    """Drop leaked jit-compile events between tests.

    In production every OSD is its own process, so the process-global
    PROFILER only ever sees one daemon's kernels. The test suite runs
    hundreds of shape-varied codec tests in ONE process; their
    perfectly legitimate compiles pool in the shared storm window and
    any cluster started later reports DEVICE_RECOMPILE_STORM, turning
    unrelated HEALTH_OK assertions flaky. Reset rebases the window
    (live mem bytes are kept — they are residency, not statistics)."""
    from ceph_tpu.common.profiler import PROFILER
    PROFILER.reset()
    yield


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: register the marker so stress-scale
    # tests (span-volume) are excluded there without unknown-mark noise
    config.addinivalue_line(
        "markers",
        "slow: stress-scale tests excluded from the tier-1 run")


def pytest_sessionfinish(session, exitstatus):
    if lockdep.violations:
        print("\nLOCKDEP: %d lock-order violation(s) detected:"
              % len(lockdep.violations))
        for v in lockdep.violations[:3]:
            print(v)


# NOTE: an earlier revision carried a `heavy` marker + --heavy gating
# here, but no test ever used it — the full suite (chaos/thrash runs
# included) finishes in ~5 minutes, so nothing is worth hiding from
# the default run. The infra was removed rather than kept as dead
# code; reintroduce it only if a genuinely multi-minute scenario ever
# lands.
