"""Test env: force the CPU backend with a virtual 8-device mesh.

Tests never require TPU hardware; sharding logic is validated on a
virtual 8-device CPU platform (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this image pre-imports jax at interpreter startup with the platform
pinned, so JAX_PLATFORMS env alone is not enough — use config.update
before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Lock-order cycle detection rides along for the WHOLE suite (the
# reference runs its qa with lockdep enabled the same way); the daemon
# locks created through common.lockdep.make_rlock become DebugRLocks.
# Violations collect rather than raise; the session-end hook surfaces
# any cycle the workload tests provoked.
from ceph_tpu.common import lockdep  # noqa: E402

lockdep.enable()


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: register the marker so stress-scale
    # tests (span-volume) are excluded there without unknown-mark noise
    config.addinivalue_line(
        "markers",
        "slow: stress-scale tests excluded from the tier-1 run")


def pytest_sessionfinish(session, exitstatus):
    if lockdep.violations:
        print("\nLOCKDEP: %d lock-order violation(s) detected:"
              % len(lockdep.violations))
        for v in lockdep.violations[:3]:
            print(v)


# NOTE: an earlier revision carried a `heavy` marker + --heavy gating
# here, but no test ever used it — the full suite (chaos/thrash runs
# included) finishes in ~5 minutes, so nothing is worth hiding from
# the default run. The infra was removed rather than kept as dead
# code; reintroduce it only if a genuinely multi-minute scenario ever
# lands.
