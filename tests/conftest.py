"""Test env: force the CPU backend with a virtual 8-device mesh.

Tests never require TPU hardware; sharding logic is validated on a
virtual 8-device CPU platform (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this image pre-imports jax at interpreter startup with the platform
pinned, so JAX_PLATFORMS env alone is not enough — use config.update
before any backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
