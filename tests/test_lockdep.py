"""Lock-order cycle detection (src/common/lockdep.cc role).

The VERDICT round-1 'done' gate: the suite runs with lockdep on (see
conftest), and a seeded inverse acquisition order provably fires."""

from __future__ import annotations

import threading

import pytest

from ceph_tpu.common import lockdep


@pytest.fixture(autouse=True)
def fresh_graph():
    # snapshot the session-wide state so these tests' seeded cycles
    # neither pollute nor ERASE what the rest of the suite collected
    # (conftest's session-end report must still see real violations)
    saved = list(lockdep.violations)
    saved_edges = {k: set(v) for k, v in lockdep._edges.items()}
    saved_reported = set(lockdep._reported)
    lockdep.reset()
    was = lockdep.enabled()
    lockdep.enable()
    yield
    lockdep.reset()
    lockdep.violations.extend(saved)
    lockdep._edges.update(saved_edges)
    lockdep._reported.update(saved_reported)
    if not was:
        lockdep.disable()


class TestSeededCycle:
    def test_inverse_order_fires(self):
        a = lockdep.DebugRLock("A")
        b = lockdep.DebugRLock("B")
        with a:
            with b:
                pass                 # establishes A -> B
        with b:
            with a:                  # B -> A closes the cycle
                pass
        assert lockdep.violations
        assert "cycle" in lockdep.violations[0]
        assert "'A'" in lockdep.violations[0]

    def test_strict_mode_raises(self):
        lockdep.enable(strict=True)
        a = lockdep.DebugRLock("SA")
        b = lockdep.DebugRLock("SB")
        with a:
            with b:
                pass
        with pytest.raises(lockdep.LockOrderError):
            with b:
                with a:
                    pass

    def test_three_way_cycle(self):
        a, b, c = (lockdep.DebugRLock(n) for n in ("X", "Y", "Z"))
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:                  # X->Y->Z->X
                pass
        assert lockdep.violations


class TestNoFalsePositives:
    def test_consistent_order_clean(self):
        a = lockdep.DebugRLock("P")
        b = lockdep.DebugRLock("Q")
        for _ in range(10):
            with a:
                with b:
                    pass
        assert not lockdep.violations

    def test_reentrant_same_name_clean(self):
        a = lockdep.DebugRLock("R")
        with a:
            with a:
                pass
        # two instances of the same lock CLASS (e.g. two PGs) nest
        # without being a self-cycle, like the reference's per-name
        # registration
        a2 = lockdep.DebugRLock("R")
        with a:
            with a2:
                pass
        assert not lockdep.violations

    def test_condition_compat(self):
        lk = lockdep.DebugRLock("cond")
        cond = threading.Condition(lk)
        hit = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                hit.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.1)
        with cond:
            cond.notify()
        t.join(timeout=5)
        assert hit == [1]
        assert not lockdep.violations


class TestDaemonPathsClean:
    def test_cluster_workload_has_no_lock_cycles(self):
        """Boot a cluster, push IO through writes/snaps/recovery, and
        assert the instrumented daemon locks (pg/osd/mon/paxos/
        backends) never form an order cycle."""
        from .cluster_util import MiniCluster, wait_until
        FAST = {"osd_heartbeat_interval": 0.1,
                "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02}
        cluster = MiniCluster(num_mons=1, num_osds=3,
                              conf_overrides=FAST).start()
        try:
            client = cluster.client()
            cluster.create_replicated_pool(client, "ld", size=3,
                                           pg_num=4)
            ioctx = client.open_ioctx("ld")
            for i in range(5):
                ioctx.write_full("o%d" % i, b"x" * 100)
            ioctx.create_snap("s")
            ioctx.write_full("o0", b"y" * 100)
            ioctx.rollback("o0", "s")
            store = cluster.stop_osd(2)
            assert wait_until(
                lambda: not cluster.leader().osdmon.osdmap.is_up(2),
                timeout=10)
            ioctx.write_full("o9", b"z")
            cluster.revive_osd(2, store=store)
            assert wait_until(cluster.all_osds_up, timeout=15)
        finally:
            cluster.stop()
        assert not lockdep.violations, lockdep.violations[:2]
