"""CephFS: MDS daemon, mdsmap monitor service, client, failover.

Mirrors the reference's fs QA surface (src/test/libcephfs/,
qa/tasks/cephfs/): namespace operations, file IO through the data
pool, metadata durability across MDS restart (journal replay), and
standby takeover when the active MDS dies.
"""

from __future__ import annotations

import errno

import pytest

from ceph_tpu.client.cephfs import CephFS, CephFSError

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02,
        "mds_beacon_interval": 0.1, "mds_beacon_grace": 0.8}


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3, conf_overrides=FAST).start()
    client = c.client()
    c.create_replicated_pool(client, "cephfs_metadata", size=2,
                             pg_num=4)
    c.create_replicated_pool(client, "cephfs_data", size=2, pg_num=4)
    res, outs, _ = client.mon_command({
        "prefix": "fs new", "fs_name": "cephfs",
        "metadata_pool": "cephfs_metadata",
        "data_pool": "cephfs_data"})
    assert res == 0, outs
    c.start_mds("a")
    c.start_mds("b")      # standby
    assert wait_until(lambda: c.mdss["a"].state == "active"
                      or c.mdss["b"].state == "active", timeout=15), \
        "no MDS ever went active"
    yield c
    c.stop()


@pytest.fixture(scope="module")
def fs(cluster):
    return CephFS(cluster.client())


class TestNamespace:
    def test_mkdir_readdir_stat(self, fs):
        fs.mkdir("/home")
        fs.mkdir("/home/alex")
        fs.mkdirs("/var/log/app")     # recursive create
        root = fs.listdir("/")
        assert "home" in root and "var" in root
        assert fs.stat("/home/alex")["type"] == "dir"
        assert fs.listdir("/var/log") == {
            "app": fs.stat("/var/log/app")}
        with pytest.raises(CephFSError) as ei:
            fs.mkdir("/home")
        assert ei.value.errno == errno.EEXIST
        with pytest.raises(CephFSError):
            fs.stat("/no/such/path")

    def test_file_write_read(self, fs):
        fs.mkdir("/data")
        payload = b"hello cephfs " * 1000
        fs.write("/data/f1", payload)
        assert fs.read("/data/f1") == payload
        assert fs.stat("/data/f1")["size"] == len(payload)
        # offset write extends; sparse gap reads as zeros
        fs.write("/data/f1", b"tail", len(payload) + 100)
        got = fs.read("/data/f1")
        assert got[:len(payload)] == payload
        assert got[len(payload):len(payload) + 100] == b"\0" * 100
        assert got.endswith(b"tail")
        # ranged read
        assert fs.read("/data/f1", 5, 6) == payload[6:11]

    def test_large_file_spans_objects(self, fs):
        """Writes larger than object_size stripe across data objects
        (the file-layout path)."""
        blob = bytes(range(256)) * (5 * 4096)   # 5 MiB > 4 MiB objects
        fs.write("/data/big", blob)
        assert fs.read("/data/big") == blob
        # the data pool really holds multiple objects for this ino
        ino = fs.stat("/data/big")["ino"]
        names = [o for o in fs.data_io.list_objects()
                 if o.startswith("%x." % ino)]
        assert len(names) >= 2

    def test_truncate(self, fs):
        fs.write("/data/trunc", b"x" * 10000)
        fs.truncate("/data/trunc", 100)
        assert fs.stat("/data/trunc")["size"] == 100
        assert fs.read("/data/trunc") == b"x" * 100
        fs.truncate("/data/trunc", 0)
        assert fs.read("/data/trunc") == b""

    def test_unlink_purges_data(self, fs):
        fs.write("/data/doomed", b"y" * 8192)
        ino = fs.stat("/data/doomed")["ino"]
        fs.unlink("/data/doomed")
        with pytest.raises(CephFSError):
            fs.stat("/data/doomed")
        def purged():
            return not [o for o in fs.data_io.list_objects()
                        if o.startswith("%x." % ino)]
        assert wait_until(purged, timeout=5), \
            "unlink left data objects behind"

    def test_rename_and_rmdir(self, fs):
        fs.mkdir("/mv")
        fs.write("/mv/old", b"contents")
        fs.rename("/mv/old", "/mv/new")
        assert fs.read("/mv/new") == b"contents"
        with pytest.raises(CephFSError):
            fs.stat("/mv/old")
        # rename across directories
        fs.mkdir("/mv/sub")
        fs.rename("/mv/new", "/mv/sub/moved")
        assert fs.read("/mv/sub/moved") == b"contents"
        # rmdir refuses non-empty, then succeeds
        with pytest.raises(CephFSError) as ei:
            fs.rmdir("/mv/sub")
        assert ei.value.errno == errno.ENOTEMPTY
        fs.unlink("/mv/sub/moved")
        fs.rmdir("/mv/sub")
        assert "sub" not in fs.listdir("/mv")

    def test_symlink(self, fs):
        fs.mkdir("/links")
        fs.write("/links/real", b"linked!")
        fs.symlink("/links/real", "/links/alias")
        assert fs.readlink("/links/alias") == "/links/real"
        assert fs.read("/links/alias") == b"linked!"
        # symlinked DIRECTORY mid-path resolves
        fs.symlink("/links", "/byway")
        assert fs.read("/byway/real") == b"linked!"

    def test_relative_symlink(self, fs):
        """A relative target resolves against the link's PARENT dir
        (Client::path_walk), not against root."""
        fs.mkdirs("/rel/deep")
        fs.write("/rel/deep/data", b"found me")
        fs.symlink("deep/data", "/rel/ptr")          # relative file
        assert fs.read("/rel/ptr") == b"found me"
        fs.symlink("deep", "/rel/dirptr")            # relative dir
        assert fs.read("/rel/dirptr/data") == b"found me"
        # relative link inside a subdir points within that subdir
        fs.symlink("data", "/rel/deep/self")
        assert fs.read("/rel/deep/self") == b"found me"

    def test_symlink_cycle_is_eloop(self, fs):
        fs.mkdir("/loop")
        fs.symlink("/loop/b", "/loop/a")
        fs.symlink("/loop/a", "/loop/b")
        with pytest.raises(CephFSError) as ei:
            fs.read("/loop/a")
        assert ei.value.errno == errno.ELOOP
        # mid-path cycle too (dir-position symlink)
        with pytest.raises(CephFSError) as ei:
            fs.stat("/loop/a/child")
        assert ei.value.errno == errno.ELOOP

    def test_rename_over_file_purges_target(self, fs):
        """Renaming over an existing file must purge the overwritten
        inode's data objects (unlink and rename share the PurgeQueue
        role) — otherwise they leak in the data pool forever."""
        from .cluster_util import wait_until
        fs.mkdir("/rrov")
        fs.write("/rrov/src", b"winner")
        fs.write("/rrov/dst", b"z" * 8192)
        doomed_ino = fs.stat("/rrov/dst")["ino"]
        fs.rename("/rrov/src", "/rrov/dst")
        assert fs.read("/rrov/dst") == b"winner"
        def purged():
            return not [o for o in fs.data_io.list_objects()
                        if o.startswith("%x." % doomed_ino)]
        assert wait_until(purged, timeout=5), \
            "rename-over-file leaked the target's data objects"

    def test_rename_dir_over_empty_dir(self, fs):
        """POSIX: dir over EMPTY dir succeeds (target removed); over a
        non-empty dir fails ENOTEMPTY."""
        fs.mkdirs("/dod/src")
        fs.write("/dod/src/payload", b"p")
        fs.mkdir("/dod/empty")
        fs.rename("/dod/src", "/dod/empty")
        assert fs.read("/dod/empty/payload") == b"p"
        assert "src" not in fs.listdir("/dod")
        fs.mkdir("/dod/other")
        with pytest.raises(CephFSError) as ei:
            fs.rename("/dod/other", "/dod/empty")   # now non-empty
        assert ei.value.errno == errno.ENOTEMPTY

    def test_rename_into_own_subtree_is_einval(self, fs):
        """Renaming a directory into its own subtree would orphan the
        subtree in a self-cycle; the MDS rejects it (EINVAL)."""
        fs.mkdirs("/cyc/a/x/y")
        fs.write("/cyc/a/payload", b"p")
        for dst in ("/cyc/a/x/y/a2", "/cyc/a/x/y"):   # deep + over-dir
            with pytest.raises(CephFSError) as ei:
                fs.rename("/cyc/a", dst)
            assert ei.value.errno == errno.EINVAL
        assert fs.read("/cyc/a/payload") == b"p"
        assert "a" in fs.listdir("/cyc")

    def test_rename_dir_over_file_is_enotdir(self, fs):
        """POSIX: renaming a directory over a non-directory fails
        ENOTDIR — and must NOT purge the file's data."""
        fs.mkdir("/dof")
        fs.mkdir("/dof/d")
        fs.write("/dof/f", b"survives")
        with pytest.raises(CephFSError) as ei:
            fs.rename("/dof/d", "/dof/f")
        assert ei.value.errno == errno.ENOTDIR
        assert fs.read("/dof/f") == b"survives"

    def test_degenerate_symlink_targets(self, fs):
        fs.mkdir("/degen")
        with pytest.raises(CephFSError) as ei:
            fs.symlink("", "/degen/empty")
        assert ei.value.errno == errno.ENOENT
        # "/" is a valid target: resolves to the root directory
        fs.symlink("/", "/degen/root")
        assert fs.stat("/degen/root")["type"] == "dir"
        assert "degen" in fs.listdir("/degen/root")

    def test_rename_to_self_is_noop(self, fs):
        """POSIX rename(p, p) succeeds and leaves the file intact —
        in particular it must NOT purge the file's own data objects
        (the destination dentry IS the source)."""
        fs.mkdir("/selfmv")
        fs.write("/selfmv/f", b"precious")
        fs.rename("/selfmv/f", "/selfmv/f")
        assert fs.read("/selfmv/f") == b"precious"

    def test_two_mounts_share_no_dedup_state(self, cluster):
        """Two CephFS mounts over ONE RadosClient must not collide in
        the MDS (session, tid) exactly-once cache: each mount starts
        tids at 1, so a shared session would answer mount B's early
        ops from mount A's cached replies."""
        client = cluster.client()
        m1 = CephFS(client)
        m2 = CephFS(client)
        assert m1.session != m2.session
        m1.mkdir("/dup_a")            # both ops run at tid 1
        m2.mkdir("/dup_b")
        root = m1.listdir("/")
        assert "dup_a" in root and "dup_b" in root


class TestDurabilityAndFailover:
    def test_metadata_survives_mds_restart(self, cluster, fs):
        fs.mkdir("/persist")
        fs.write("/persist/file", b"durable" * 100)
        active = "a" if cluster.mdss["a"].state == "active" else "b"
        standby = "b" if active == "a" else "a"
        # stop BOTH, restart one: state must come back from RADOS +
        # journal replay alone
        cluster.stop_mds(standby)
        cluster.stop_mds(active)
        mds = cluster.start_mds("c")
        assert wait_until(lambda: mds.state == "active", timeout=15), \
            "restarted MDS never took the rank"
        assert fs.read("/persist/file") == b"durable" * 100
        assert fs.stat("/persist")["type"] == "dir"
        fs.write("/persist/after", b"new-epoch")
        assert fs.read("/persist/after") == b"new-epoch"
        cluster.start_mds("d")        # restore a standby for later

    def test_standby_takeover_on_active_death(self, cluster, fs):
        fs.write("/persist/ha", b"failover-safe")
        names = list(cluster.mdss)
        active = next(n for n in names
                      if cluster.mdss[n].state == "active")
        cluster.stop_mds(active)      # kill the active, no warning
        def new_active():
            return any(m.state == "active"
                       for m in cluster.mdss.values())
        assert wait_until(new_active, timeout=15), \
            "standby was never promoted"
        # the namespace survives and serves through the new active
        assert fs.read("/persist/ha") == b"failover-safe"
        fs.write("/persist/ha2", b"post-failover")
        assert fs.read("/persist/ha2") == b"post-failover"

    def test_mds_stat_command(self, cluster):
        client = cluster.client()
        res, _, data = client.mon_command({"prefix": "mds stat"})
        assert res == 0
        assert data["active"] is not None
        assert data["fs"]["metadata_pool"] == "cephfs_metadata"
