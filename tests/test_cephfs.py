"""CephFS: MDS daemon, mdsmap monitor service, client, failover.

Mirrors the reference's fs QA surface (src/test/libcephfs/,
qa/tasks/cephfs/): namespace operations, file IO through the data
pool, metadata durability across MDS restart (journal replay), and
standby takeover when the active MDS dies.
"""

from __future__ import annotations

import errno

import pytest

from ceph_tpu.client.cephfs import CephFS, CephFSError

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02,
        "mds_beacon_interval": 0.1, "mds_beacon_grace": 0.8}


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(num_mons=1, num_osds=3, conf_overrides=FAST).start()
    client = c.client()
    c.create_replicated_pool(client, "cephfs_metadata", size=2,
                             pg_num=4)
    c.create_replicated_pool(client, "cephfs_data", size=2, pg_num=4)
    res, outs, _ = client.mon_command({
        "prefix": "fs new", "fs_name": "cephfs",
        "metadata_pool": "cephfs_metadata",
        "data_pool": "cephfs_data"})
    assert res == 0, outs
    c.start_mds("a")
    c.start_mds("b")      # standby
    assert wait_until(lambda: c.mdss["a"].state == "active"
                      or c.mdss["b"].state == "active", timeout=15), \
        "no MDS ever went active"
    yield c
    c.stop()


@pytest.fixture(scope="module")
def fs(cluster):
    return CephFS(cluster.client())


class TestNamespace:
    def test_mkdir_readdir_stat(self, fs):
        fs.mkdir("/home")
        fs.mkdir("/home/alex")
        fs.mkdirs("/var/log/app")     # recursive create
        root = fs.listdir("/")
        assert "home" in root and "var" in root
        assert fs.stat("/home/alex")["type"] == "dir"
        assert fs.listdir("/var/log") == {
            "app": fs.stat("/var/log/app")}
        with pytest.raises(CephFSError) as ei:
            fs.mkdir("/home")
        assert ei.value.errno == errno.EEXIST
        with pytest.raises(CephFSError):
            fs.stat("/no/such/path")

    def test_file_write_read(self, fs):
        fs.mkdir("/data")
        payload = b"hello cephfs " * 1000
        fs.write("/data/f1", payload)
        assert fs.read("/data/f1") == payload
        assert fs.stat("/data/f1")["size"] == len(payload)
        # offset write extends; sparse gap reads as zeros
        fs.write("/data/f1", b"tail", len(payload) + 100)
        got = fs.read("/data/f1")
        assert got[:len(payload)] == payload
        assert got[len(payload):len(payload) + 100] == b"\0" * 100
        assert got.endswith(b"tail")
        # ranged read
        assert fs.read("/data/f1", 5, 6) == payload[6:11]

    def test_large_file_spans_objects(self, fs):
        """Writes larger than object_size stripe across data objects
        (the file-layout path)."""
        blob = bytes(range(256)) * (5 * 4096)   # 5 MiB > 4 MiB objects
        fs.write("/data/big", blob)
        assert fs.read("/data/big") == blob
        # the data pool really holds multiple objects for this ino
        ino = fs.stat("/data/big")["ino"]
        names = [o for o in fs.data_io.list_objects()
                 if o.startswith("%x." % ino)]
        assert len(names) >= 2

    def test_truncate(self, fs):
        fs.write("/data/trunc", b"x" * 10000)
        fs.truncate("/data/trunc", 100)
        assert fs.stat("/data/trunc")["size"] == 100
        assert fs.read("/data/trunc") == b"x" * 100
        fs.truncate("/data/trunc", 0)
        assert fs.read("/data/trunc") == b""

    def test_unlink_purges_data(self, fs):
        fs.write("/data/doomed", b"y" * 8192)
        ino = fs.stat("/data/doomed")["ino"]
        fs.unlink("/data/doomed")
        with pytest.raises(CephFSError):
            fs.stat("/data/doomed")
        def purged():
            return not [o for o in fs.data_io.list_objects()
                        if o.startswith("%x." % ino)]
        assert wait_until(purged, timeout=5), \
            "unlink left data objects behind"

    def test_rename_and_rmdir(self, fs):
        fs.mkdir("/mv")
        fs.write("/mv/old", b"contents")
        fs.rename("/mv/old", "/mv/new")
        assert fs.read("/mv/new") == b"contents"
        with pytest.raises(CephFSError):
            fs.stat("/mv/old")
        # rename across directories
        fs.mkdir("/mv/sub")
        fs.rename("/mv/new", "/mv/sub/moved")
        assert fs.read("/mv/sub/moved") == b"contents"
        # rmdir refuses non-empty, then succeeds
        with pytest.raises(CephFSError) as ei:
            fs.rmdir("/mv/sub")
        assert ei.value.errno == errno.ENOTEMPTY
        fs.unlink("/mv/sub/moved")
        fs.rmdir("/mv/sub")
        assert "sub" not in fs.listdir("/mv")

    def test_symlink(self, fs):
        fs.mkdir("/links")
        fs.write("/links/real", b"linked!")
        fs.symlink("/links/real", "/links/alias")
        assert fs.readlink("/links/alias") == "/links/real"
        assert fs.read("/links/alias") == b"linked!"
        # symlinked DIRECTORY mid-path resolves
        fs.symlink("/links", "/byway")
        assert fs.read("/byway/real") == b"linked!"


class TestDurabilityAndFailover:
    def test_metadata_survives_mds_restart(self, cluster, fs):
        fs.mkdir("/persist")
        fs.write("/persist/file", b"durable" * 100)
        active = "a" if cluster.mdss["a"].state == "active" else "b"
        standby = "b" if active == "a" else "a"
        # stop BOTH, restart one: state must come back from RADOS +
        # journal replay alone
        cluster.stop_mds(standby)
        cluster.stop_mds(active)
        mds = cluster.start_mds("c")
        assert wait_until(lambda: mds.state == "active", timeout=15), \
            "restarted MDS never took the rank"
        assert fs.read("/persist/file") == b"durable" * 100
        assert fs.stat("/persist")["type"] == "dir"
        fs.write("/persist/after", b"new-epoch")
        assert fs.read("/persist/after") == b"new-epoch"
        cluster.start_mds("d")        # restore a standby for later

    def test_standby_takeover_on_active_death(self, cluster, fs):
        fs.write("/persist/ha", b"failover-safe")
        names = list(cluster.mdss)
        active = next(n for n in names
                      if cluster.mdss[n].state == "active")
        cluster.stop_mds(active)      # kill the active, no warning
        def new_active():
            return any(m.state == "active"
                       for m in cluster.mdss.values())
        assert wait_until(new_active, timeout=15), \
            "standby was never promoted"
        # the namespace survives and serves through the new active
        assert fs.read("/persist/ha") == b"failover-safe"
        fs.write("/persist/ha2", b"post-failover")
        assert fs.read("/persist/ha2") == b"post-failover"

    def test_mds_stat_command(self, cluster):
        client = cluster.client()
        res, _, data = client.mon_command({"prefix": "mds stat"})
        assert res == 0
        assert data["active"] is not None
        assert data["fs"]["metadata_pool"] == "cephfs_metadata"
