"""FileStore + FileDB durability tests.

Models the reference's journal-replay coverage
(src/test/objectstore/store_test.cc and FileJournal tests): write-ahead
commit semantics, crash recovery by replay, torn/corrupt journal tails,
checkpoint + trim, and the KV write-ahead log.
"""

import os
from ceph_tpu import encoding
import struct

import pytest

from ceph_tpu import compressor as ceph_compressor
from ceph_tpu.store import FileDB, FileStore, Transaction

# checkpoint-compression tests prefer zstd (the reference's default) but
# degrade to zlib when the zstandard host library is absent
BEST_COMPRESSOR = "zstd" if ceph_compressor.available("zstd") else "zlib"


def make_store(path, **kw):
    st = FileStore(str(path), journal_sync=False, **kw)
    st.mount()
    return st


def write_obj(st, cid, oid, data, commit_log=None):
    t = Transaction()
    t.create_collection(cid)
    t.write(cid, oid, 0, data)
    t.setattr(cid, oid, "hinfo", b"meta")
    t.omap_setkeys(cid, oid, {"k": b"v"})
    if commit_log is not None:
        t.register_on_commit(lambda: commit_log.append(oid))
    st.queue_transaction(t)


class TestFileStore:
    def test_write_read_roundtrip(self, tmp_path):
        st = make_store(tmp_path)
        commits = []
        write_obj(st, "pg1", "obj1", b"hello world", commits)
        assert commits == ["obj1"]   # journal-ahead: commit fired
        assert st.read("pg1", "obj1") == b"hello world"
        assert st.getattr("pg1", "obj1", "hinfo") == b"meta"
        assert st.omap_get("pg1", "obj1") == {"k": b"v"}
        st.umount()

    def test_crash_before_sync_replays_journal(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"payload-1")
        write_obj(st, "pg1", "obj2", b"payload-2")
        # crash: no sync(), no umount() — reopen the same directory
        st2 = make_store(tmp_path)
        assert st2.read("pg1", "obj1") == b"payload-1"
        assert st2.read("pg1", "obj2") == b"payload-2"
        assert st2.list_collections() == ["pg1"]
        st2.umount()

    def test_sync_checkpoint_then_crash(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"checkpointed")
        st.sync()
        assert os.path.getsize(st.journal_path) == 0  # trimmed
        write_obj(st, "pg1", "obj2", b"journaled-only")
        st2 = make_store(tmp_path)
        assert st2.read("pg1", "obj1") == b"checkpointed"
        assert st2.read("pg1", "obj2") == b"journaled-only"
        st2.umount()

    def test_torn_journal_tail_recovers_prefix(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"good entry")
        write_obj(st, "pg1", "obj2", b"torn entry")
        st._journal._fd.flush()
        # tear the last entry: truncate mid-payload
        size = os.path.getsize(st.journal_path)
        with open(st.journal_path, "r+b") as f:
            f.truncate(size - 7)
        st2 = FileStore(str(tmp_path))
        st2.mount()
        assert st2.read("pg1", "obj1") == b"good entry"
        assert not st2.exists("pg1", "obj2")
        st2.umount()

    def test_corrupt_crc_stops_replay(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"first")
        write_obj(st, "pg1", "obj2", b"second")
        st._journal._fd.flush()
        # flip one payload byte of the second entry
        hdr = struct.Struct("<III")
        with open(st.journal_path, "r+b") as f:
            raw = f.read()
            _, length, _ = hdr.unpack(raw[:hdr.size])
            off = hdr.size + length + hdr.size + 2   # inside entry 2
            f.seek(off)
            byte = raw[off] ^ 0xFF
            f.write(bytes([byte]))
        st2 = FileStore(str(tmp_path))
        st2.mount()
        assert st2.read("pg1", "obj1") == b"first"
        assert not st2.exists("pg1", "obj2")
        st2.umount()

    def test_writes_after_torn_tail_recovery_are_replayable(self, tmp_path):
        """Recovery must truncate the garbage: writes acknowledged after
        a torn-tail mount must survive the NEXT crash too."""
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"before crash 1")
        write_obj(st, "pg1", "obj2", b"will be torn")
        st._journal._fd.flush()
        size = os.path.getsize(st.journal_path)
        with open(st.journal_path, "r+b") as f:
            f.truncate(size - 5)
        # crash 1 -> recovery mount; write more; crash 2 (no sync)
        st2 = make_store(tmp_path)
        write_obj(st2, "pg1", "obj3", b"after recovery")
        st3 = make_store(tmp_path)
        assert st3.read("pg1", "obj1") == b"before crash 1"
        assert st3.read("pg1", "obj3") == b"after recovery"
        assert not st3.exists("pg1", "obj2")
        st3.umount()

    def test_remove_and_remove_collection_survive_restart(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "obj1", b"a")
        write_obj(st, "pg2", "obj2", b"b")
        st.sync()
        t = Transaction()
        t.remove("pg1", "obj1")
        st.queue_transaction(t)
        t = Transaction()
        t.remove_collection("pg2")
        st.queue_transaction(t)
        st.sync()
        st.umount()
        st2 = make_store(tmp_path)
        assert not st2.exists("pg1", "obj1")
        assert st2.list_collections() == ["pg1"]
        st2.umount()

    def test_clone_truncate_zero_move(self, tmp_path):
        st = make_store(tmp_path)
        write_obj(st, "pg1", "src", b"0123456789")
        t = Transaction()
        t.clone("pg1", "src", "dst")
        t.truncate("pg1", "dst", 6)
        t.zero("pg1", "dst", 2, 2)
        t.collection_move_rename("pg1", "dst", "pg1", "moved")
        st.queue_transaction(t)
        st.umount()
        st2 = make_store(tmp_path)
        assert st2.read("pg1", "moved") == b"01\0\0 45".replace(b" ", b"")
        assert not st2.exists("pg1", "dst")
        st2.umount()

    def test_bare_clone_survives_sync_and_remount(self, tmp_path):
        """A clone with no further writes to the destination must still
        be checkpointed (the dst is dirty even though no op names it as
        (op[1], op[2]))."""
        st = make_store(tmp_path)
        write_obj(st, "pg1", "src", b"cloneme")
        t = Transaction()
        t.clone("pg1", "src", "dst")
        st.queue_transaction(t)
        st.sync()   # trims the journal holding the clone op
        st.umount()
        st2 = make_store(tmp_path)
        assert st2.read("pg1", "dst") == b"cloneme"
        st2.umount()

    def test_autosync_threshold(self, tmp_path):
        st = make_store(tmp_path, sync_threshold=1024)
        for i in range(8):
            write_obj(st, "pg1", "obj%d" % i, b"x" * 512)
        # the journal can never exceed threshold + one entry
        assert os.path.getsize(st.journal_path) < 2048
        st.umount()

    def test_unmounted_store_rejects_writes(self, tmp_path):
        st = FileStore(str(tmp_path))
        with pytest.raises(RuntimeError):
            st.queue_transaction(Transaction())


class TestFileStoreCompression:
    def test_checkpoint_compression_roundtrip(self, tmp_path):
        """Compressible object data is stored compressed in the
        checkpoint (bluestore blob compression analog) and transparently
        decompressed on mount."""
        st = FileStore(str(tmp_path), journal_sync=False,
                       compression=BEST_COMPRESSOR)
        st.mount()
        compressible = b"pattern " * 8192     # 64k, highly compressible
        write_obj(st, "pg1", "zip", compressible)
        st.sync()
        st.umount()
        blob_sizes = sum(
            os.path.getsize(os.path.join(st.current_dir, f))
            for f in os.listdir(st.current_dir))
        assert blob_sizes < len(compressible) // 4
        st2 = FileStore(str(tmp_path), compression=BEST_COMPRESSOR)
        st2.mount()
        assert st2.read("pg1", "zip") == compressible
        st2.umount()

    def test_incompressible_stored_raw_and_readable(self, tmp_path):
        import numpy as np
        st = FileStore(str(tmp_path), journal_sync=False,
                       compression="zlib")
        st.mount()
        noise = bytes(np.random.default_rng(3).integers(
            0, 256, 1 << 16, dtype=np.uint8))
        write_obj(st, "pg1", "raw", noise)
        st.sync()
        st.umount()
        # a plain (compression=none) reopen still reads it: raw blobs
        # carry no compression tag
        st2 = FileStore(str(tmp_path))
        st2.mount()
        assert st2.read("pg1", "raw") == noise
        st2.umount()

    def test_compressed_checkpoint_readable_without_config(self, tmp_path):
        """The compression algorithm rides in each blob's metadata, so
        a store reopened without compression configured still reads
        compressed checkpoints."""
        st = FileStore(str(tmp_path), journal_sync=False,
                       compression=BEST_COMPRESSOR)
        st.mount()
        write_obj(st, "pg1", "zip", b"z" * 50000)
        st.sync()
        st.umount()
        st2 = FileStore(str(tmp_path))   # no compression configured
        st2.mount()
        assert st2.read("pg1", "zip") == b"z" * 50000
        st2.umount()


class TestFileStoreInCluster:
    def test_osd_data_survives_daemon_restart(self, tmp_path):
        """An OSD backed by FileStore keeps its shards across a hard
        kill + revive on the same directory (the FileStore promise the
        MemStore harness cannot make)."""
        from .cluster_util import MiniCluster, wait_until
        FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
                "mon_osd_down_out_interval": 1.0,
                "paxos_propose_interval": 0.02}
        cluster = MiniCluster(num_mons=1, num_osds=0, conf_overrides=FAST)
        for rank in cluster.monmap:
            from ceph_tpu.common.context import Context
            from ceph_tpu.mon.monitor import Monitor
            mon = Monitor(rank, cluster.monmap,
                          Context(FAST, name="mon.%d" % rank))
            mon.init()
            cluster.mons.append(mon)
        assert wait_until(lambda: any(m.is_leader() for m in cluster.mons))
        stores = {}
        try:
            for osd_id in range(3):
                path = tmp_path / ("osd.%d" % osd_id)
                path.mkdir()
                stores[osd_id] = FileStore(str(path), journal_sync=False)
                stores[osd_id].mount()
                cluster.start_osd(osd_id, store=stores[osd_id])
            cluster.num_osds = 3
            assert wait_until(cluster.all_osds_up, timeout=15)
            client = cluster.client()
            cluster.create_replicated_pool(client, "durable", size=3,
                                           pg_num=4)
            ioctx = client.open_ioctx("durable")
            payload = b"persistent payload " * 50
            ioctx.write_full("pobj", payload)
            assert ioctx.read("pobj") == payload
            # hard-kill osd.0, reopen its directory as a NEW FileStore
            # (fresh process analog: memory state comes only from disk)
            cluster.stop_osd(0)
            stores[0].umount() if stores[0].mounted else None
            reopened = FileStore(str(tmp_path / "osd.0"),
                                 journal_sync=False)
            reopened.mount()
            cluster.revive_osd(0, store=reopened)
            assert wait_until(cluster.all_osds_up, timeout=15)
            assert ioctx.read("pobj") == payload
            # the revived OSD's own store really holds the object data
            total = sum(
                len(reopened.read(cid, oid))
                for cid in reopened.list_collections()
                for oid in reopened.list_objects(cid))
            assert total >= len(payload)
        finally:
            cluster.stop()


class TestFileDB:
    def test_wal_replay_after_crash(self, tmp_path):
        db = FileDB(str(tmp_path), log_sync=False).open()
        b = db.get_transaction()
        b.set("osdmap", "epoch_1", b"mapdata")
        b.set("paxos", "42", b"value")
        db.submit_transaction(b)
        # crash: reopen without close()
        db2 = FileDB(str(tmp_path)).open()
        assert db2.get("osdmap", "epoch_1") == b"mapdata"
        assert db2.get("paxos", "42") == b"value"
        db2.close()

    def test_compact_and_reload(self, tmp_path):
        db = FileDB(str(tmp_path), log_sync=False).open()
        for i in range(10):
            b = db.get_transaction()
            b.set("p", "k%02d" % i, b"v%d" % i)
            db.submit_transaction(b)
        db.compact()
        assert os.path.getsize(db.log_path) == 0
        b = db.get_transaction()
        b.rmkey("p", "k03")
        db.submit_transaction(b)
        db.close()
        db2 = FileDB(str(tmp_path)).open()
        assert db2.get("p", "k00") == b"v0"
        assert db2.get("p", "k03") is None
        assert [k for k, _ in db2.get_iterator("p")] == sorted(
            "k%02d" % i for i in range(10) if i != 3)
        db2.close()

    def test_torn_log_tail(self, tmp_path):
        db = FileDB(str(tmp_path), log_sync=False).open()
        for i in range(3):
            b = db.get_transaction()
            b.set("p", "k%d" % i, b"v")
            db.submit_transaction(b)
        db._log._fd.flush()
        size = os.path.getsize(db.log_path)
        with open(db.log_path, "r+b") as f:
            f.truncate(size - 3)
        db2 = FileDB(str(tmp_path), log_sync=False).open()
        assert db2.get("p", "k0") == b"v"
        assert db2.get("p", "k1") == b"v"
        assert db2.get("p", "k2") is None
        # post-recovery writes go after the truncated tail and replay
        b = db2.get_transaction()
        b.set("p", "k9", b"post")
        db2.submit_transaction(b)
        db3 = FileDB(str(tmp_path)).open()
        assert db3.get("p", "k9") == b"post"
        db3.close()

    def test_rm_prefix_persists(self, tmp_path):
        db = FileDB(str(tmp_path), log_sync=False).open()
        b = db.get_transaction()
        b.set("a", "x", b"1")
        b.set("b", "y", b"2")
        db.submit_transaction(b)
        b = db.get_transaction()
        b.rmkeys_by_prefix("a")
        db.submit_transaction(b)
        db.close()
        db2 = FileDB(str(tmp_path)).open()
        assert db2.get("a", "x") is None
        assert db2.get("b", "y") == b"2"
        db2.close()
