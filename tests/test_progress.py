"""Recovery-convergence observability tests.

Covers the mgr progress module (pybind/mgr/progress analog: osdmap
diffs open events, aggregated PG stats drive a MONOTONE completion
fraction with a rate-based ETA, completed events retire into a
bounded ring), the mon event journal (`ceph events last/watch`), the
new Prometheus recovery series with their ageout discipline, and an
exposition-format lint over the full rendered page.
"""

import threading
import time

import pytest

from ceph_tpu.mgr import PrometheusModule, StatusModule
from ceph_tpu.mgr.modules import _escape_label
from ceph_tpu.mgr.progress import IDLE_GRACE, ProgressModule

from .cluster_util import MiniCluster, wait_until

FAST = {"osd_heartbeat_interval": 0.1, "osd_heartbeat_grace": 0.6,
        "mon_osd_down_out_interval": 1.0,
        "paxos_propose_interval": 0.02,
        "mgr_stats_period": 0.25}


# -- unit scaffolding: a module with no mgr/network behind it ----------

class _Conf:
    def get_val(self, key):
        raise KeyError(key)


class _Ctx:
    conf = _Conf()


class _FakeMgr:
    ctx = _Ctx()
    mon_client = None


def _module() -> ProgressModule:
    mod = ProgressModule(_FakeMgr())
    mod._journal = lambda *a, **k: None   # unit tests: no mon to post to
    return mod


class _FakeMap:
    def __init__(self, max_osd, in_set, up_set, pools=None):
        self.max_osd = max_osd
        self._in = set(in_set)
        self._up = set(up_set)
        self.pools = pools or {}

    def exists(self, o):
        return True

    def is_in(self, o):
        return o in self._in

    def is_up(self, o):
        return o in self._up


class TestFractionOracle:
    def test_monotone_fraction_from_pg_stat_deltas(self):
        """Exact oracle: fraction = max(prev, 1 - bad/peak_bad), and a
        mid-recovery re-peer that re-raises bad must raise the
        BASELINE, never walk the bar backwards."""
        mod = _module()
        ev = mod._open_event("Rebalancing after osd.3 marked out",
                             now=0.0)
        feed = [  # (t, bad, want_fraction)
            (0.5, 12, 0.0),       # peak damage -> baseline 12
            (1.0, 9, 0.25),
            (1.5, 6, 0.5),
            (2.0, 16, 0.5),       # re-peer: baseline -> 16, bar holds
            (2.5, 8, 0.5),        # 1 - 8/16
            (3.0, 4, 0.75),
            (3.5, 0, 0.99),       # first zero: capped, not yet done
            (4.0, 0, 1.0),        # second zero: converged
        ]
        for t, bad, want in feed:
            mod._update_one(ev, bad, False, t, [])
            assert ev["fraction"] == pytest.approx(want), (t, bad)
        hist = [f for _, f in ev["history"]]
        assert hist == sorted(hist), "fraction history regressed"
        assert hist[-1] == 1.0

    def test_peering_holds_completion(self):
        mod = _module()
        ev = mod._open_event("x", now=0.0)
        mod._update_one(ev, 4, False, 0.5, [])
        mod._update_one(ev, 0, True, 1.0, [])
        assert ev["fraction"] == 0.99     # zero bad, but still peering
        mod._update_one(ev, 0, True, 1.5, [])
        assert ev["fraction"] == 0.99
        mod._update_one(ev, 0, False, 2.0, [])
        assert ev["fraction"] == 1.0

    def test_no_damage_event_completes_after_idle_grace(self):
        """A change that moved nothing (empty pool resized) completes
        after the idle grace instead of hanging at 0% forever."""
        mod = _module()
        ev = mod._open_event("resize", now=0.0)
        mod._update_one(ev, 0, False, 0.5, [])
        mod._update_one(ev, 0, False, 1.0, [])
        assert ev["fraction"] < 1.0       # streak ok, grace not elapsed
        mod._update_one(ev, 0, False, IDLE_GRACE + 0.1, [])
        assert ev["fraction"] == 1.0

    def test_update_folds_degraded_plus_misplaced(self):
        """The end-to-end derivation: update() reads the aggregator's
        pg_summary and folds degraded+misplaced into the fraction,
        retiring the event at convergence."""
        mod = _module()
        summaries = iter([
            {"degraded_objects": 6, "misplaced_objects": 2, "pgs": {}},
            {"degraded_objects": 2, "misplaced_objects": 2, "pgs": {}},
            {"degraded_objects": 0, "misplaced_objects": 0, "pgs": {}},
            {"degraded_objects": 0, "misplaced_objects": 0, "pgs": {}},
        ])

        class _Metrics:
            @staticmethod
            def pg_summary():
                return next(summaries)

        mod.get = lambda name: _Metrics()
        ev = mod._open_event("x", now=0.0)
        mod.update(now=0.5)
        assert ev["fraction"] == 0.0          # baseline 8
        mod.update(now=1.0)
        assert ev["fraction"] == 0.5          # 1 - 4/8
        mod.update(now=1.5)
        assert ev["fraction"] == 0.99
        mod.update(now=2.0)
        assert not mod.active_events()
        done = mod.completed[-1]
        assert done["fraction"] == 1.0
        assert done["duration"] == 2.0

    def test_eta_from_recent_slope(self):
        mod = _module()
        ev = mod._open_event("x", now=0.0)
        mod._update_one(ev, 20, False, 0.0, [])
        mod._update_one(ev, 10, False, 1.0, [])
        assert ev["fraction"] == 0.5
        # half done in 1s at a steady rate -> 1s left
        assert ev["eta"] == pytest.approx(1.0, abs=0.05)

    def test_eta_none_without_progress(self):
        mod = _module()
        ev = mod._open_event("x", now=0.0)
        mod._update_one(ev, 10, False, 0.0, [])
        mod._update_one(ev, 10, False, 1.0, [])
        assert ev["eta"] is None

    def test_completed_ring_retention(self):
        mod = _module()
        assert mod.completed.maxlen == 32     # conf default

        class _Metrics:
            @staticmethod
            def pg_summary():
                return {"degraded_objects": 0, "misplaced_objects": 0,
                        "pgs": {}}

        mod.get = lambda name: _Metrics()
        for i in range(40):
            mod._open_event("ev %d" % i, now=0.0)
        mod.update(now=100.0)
        mod.update(now=100.5)     # second clean round past the grace
        assert not mod.active_events()
        assert len(mod.completed) == mod.completed.maxlen == 32
        # the bounded ring keeps the NEWEST completions
        assert mod.completed[-1]["message"] == "ev 39"
        assert mod.completed[0]["message"] == "ev 8"

    def test_osdmap_diff_opens_events(self):
        mod = _module()
        mod._on_osdmap(_FakeMap(4, {0, 1, 2, 3}, {0, 1, 2, 3}))
        assert mod.active_events() == []      # boot map: no change
        mod._on_osdmap(_FakeMap(4, {0, 1, 3}, {0, 1, 3}))
        msgs = [ev["message"] for ev in mod.active_events()]
        assert msgs == ["Rebalancing after osd.2 marked out"]
        mod._on_osdmap(_FakeMap(4, {0, 1, 2, 3}, {0, 1, 2, 3}))
        msgs = [ev["message"] for ev in mod.active_events()]
        assert "Rebalancing after osd.2 marked in" in msgs

    def test_render_bars_format(self):
        mod = _module()
        ev = mod._open_event("Rebalancing after osd.2 marked out",
                             now=0.0)
        ev["fraction"], ev["eta"] = 0.42, 3.1
        assert mod.render_bars() == [
            "[====>.....] 42% Rebalancing after osd.2 marked out"
            ", ETA 3.1s"]
        ev["fraction"], ev["eta"] = 1.0, None
        assert mod.render_bars() == [
            "[==========] 100% Rebalancing after osd.2 marked out"]


# -- live cluster: the osd-out lifecycle end to end --------------------

@pytest.fixture(scope="module")
def conv_cluster():
    cluster = MiniCluster(num_mons=1, num_osds=4,
                          conf_overrides=FAST).start()
    mgr = cluster.start_mgr(modules=(ProgressModule, StatusModule,
                                     PrometheusModule))
    client = cluster.client()
    pool_id = cluster.create_replicated_pool(client, "convp", size=3,
                                             pg_num=8)
    assert cluster.wait_clean(pool_id)
    io = client.open_ioctx("convp")
    for i in range(16):
        io.write_full("obj%d" % i, b"q" * 4096)
    assert wait_until(lambda: mgr.osdmap is not None, timeout=10)
    yield cluster, mgr, client, pool_id
    cluster.stop()


class TestProgressLive:
    def test_osd_out_event_lifecycle(self, conv_cluster):
        """osd out -> event opens -> recovery drains -> event retires
        at 1.0 with a monotone history (the ISSUE's core sequence)."""
        cluster, mgr, client, pool_id = conv_cluster
        progress = mgr.modules["progress"]
        victim = max(cluster.osds)
        store = cluster.stop_osd(victim)
        try:
            assert wait_until(
                lambda: not cluster.leader().osdmon.osdmap
                .is_in(victim), timeout=30), "osd never marked out"
            needle = "osd.%d marked out" % victim
            assert wait_until(
                lambda: any(needle in ev["message"] for ev in
                            progress.active_events()
                            + progress.completed_events()),
                timeout=15), "no progress event opened"

            def completed_out():
                return [ev for ev in progress.completed_events()
                        if needle in ev["message"]]
            assert wait_until(lambda: bool(completed_out()),
                              timeout=60), \
                "event never completed: %s" % progress.active_events()
            ev = completed_out()[0]
            hist = [f for _, f in ev["history"]]
            assert all(b >= a for a, b in zip(hist, hist[1:])), hist
            assert hist[-1] == 1.0
            assert ev["fraction"] == 1.0
            assert ev["duration"] > 0
        finally:
            cluster.revive_osd(victim, store=store)
            client.mon_command({"prefix": "osd in", "id": victim})
            assert wait_until(cluster.all_osds_up, timeout=30)
        # the revive opens a marked-in event; everything must retire
        # once the cluster is clean again
        assert wait_until(lambda: not progress.active_events(),
                          timeout=60), progress.active_events()

    def test_journal_interleaves_osdmap_and_progress(self, conv_cluster):
        """The mon event journal carries BOTH the osdmap change and
        the mgr's progress narration of it, in seq order."""
        _, _, client, _ = conv_cluster

        def entries():
            res, _, tail = client.mon_command(
                {"prefix": "events last", "num": 500})
            assert res == 0
            return tail or []

        assert wait_until(
            lambda: {"osdmap", "progress"} <=
            {e["type"] for e in entries()}, timeout=15)
        tail = entries()
        out_seq = min(e["seq"] for e in tail if e["type"] == "osdmap"
                      and "marked out" in e["message"])
        prog = [e for e in tail if e["type"] == "progress"
                and "marked out" in e["message"]]
        assert prog, tail
        # cause before effect: the map change journals before the
        # progress events narrating it
        assert all(e["seq"] > out_seq for e in prog)
        seqs = [e["seq"] for e in tail]
        assert seqs == sorted(seqs)

    def test_status_shows_recovery_io_and_progress(self, conv_cluster):
        _, mgr, _, _ = conv_cluster
        progress = mgr.modules["progress"]
        ev = progress._open_event("status bar probe")
        ev["fraction"], ev["eta"] = 0.5, 2.0
        try:
            rc, out, _ = mgr.module_command({"prefix": "status"})
        finally:
            with progress._lock:
                progress._events.pop(ev["id"], None)
        assert rc == 0
        assert "client:" in out and "recovery:" in out
        assert "progress:" in out
        # a concurrent update() may recompute the ETA; the bar itself
        # is deterministic (monotone fraction holds at 50%)
        assert "[=====>....] 50% status bar probe" in out

    def test_progress_command(self, conv_cluster):
        _, mgr, _, _ = conv_cluster
        rc, out, _ = mgr.module_command({"prefix": "progress"})
        assert rc == 0
        # after the lifecycle test the completed ring narrates it
        assert "[complete]" in out or "no active progress" in out

    def test_prometheus_series_appear_then_age_out(self, conv_cluster):
        cluster, mgr, _, _ = conv_cluster
        prom = mgr.modules["prometheus"]
        progress = mgr.modules["progress"]
        assert wait_until(
            lambda: mgr.metrics.pg_summary()["pgs"], timeout=15), \
            "pg stats never reached the aggregator"
        text = prom.render()
        assert "ceph_recovery_bytes_rate" in text
        assert "ceph_pg_degraded_objects{" in text
        assert "ceph_pg_misplaced_objects{" in text
        # an active event exports its fraction ...
        ev = progress._open_event("synthetic export probe")
        ev_id = ev["id"]
        try:
            text = prom.render()
            assert ('ceph_progress_event_fraction{event_id="%s"}'
                    % ev_id) in text
        finally:
            # ... and the series leaves the exposition the moment the
            # event completes (the ageout discipline)
            with progress._lock:
                progress._events.pop(ev_id, None)
                progress.completed.append(ev)
        text = prom.render()
        assert 'event_id="%s"' % ev_id not in text


class TestEventsCLI:
    def test_events_last(self, conv_cluster, capsys):
        cluster, _, _, _ = conv_cluster
        from ceph_tpu.tools import ceph_cli
        mon_addr = "%s:%d" % cluster.monmap[0]
        assert ceph_cli.main(
            ["--mon", mon_addr, "events", "last", "50"]) == 0
        out = capsys.readouterr().out
        assert "[osdmap]" in out      # pool create / osd out traffic

    def test_events_watch_streams_new_events(self, conv_cluster,
                                             capsys):
        cluster, _, client, _ = conv_cluster
        from ceph_tpu.tools import ceph_cli
        mon_addr = "%s:%d" % cluster.monmap[0]
        result = {}

        def watch():
            result["rc"] = ceph_cli.main(
                ["--mon", mon_addr, "--count", "2", "--period", "0.1",
                 "events", "watch"])

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.5)               # watcher takes its seq floor
        for i in range(2):
            res, outs, _ = client.mon_command(
                {"prefix": "events append", "type": "test",
                 "message": "watch probe %d" % i, "data": {}})
            assert res == 0 and outs == "appended"
        t.join(timeout=90)
        assert not t.is_alive(), "events watch never returned"
        assert result["rc"] == 0
        out = capsys.readouterr().out
        assert "watch probe" in out


# -- exposition lint ---------------------------------------------------
# The checker lives in cluster_util so every suite rendering the page
# (progress, perf_query, scaleobs) lints with the same contract.

from .cluster_util import lint_exposition as _lint_exposition  # noqa: E402


class TestExpositionLint:
    def test_escape_label(self):
        assert _escape_label('a"b') == 'a\\"b'
        assert _escape_label("a\nb") == "a\\nb"
        assert _escape_label("a\\b") == "a\\\\b"

    def test_rendered_page_passes_lint(self, conv_cluster):
        """Lint the FULL live page, with a label-hostile PG id fed
        through the aggregator so the escaping path is on the page."""
        _, mgr, _, _ = conv_cluster
        mgr.metrics.record(
            "osd.99", {"osd": {}},
            pg_stats={'9.0"\nq\\': {"state": "active",
                                    "degraded_objects": 1,
                                    "misplaced_objects": 0}},
            daemon_type="osd")
        prom = mgr.modules["prometheus"]
        text = prom.render()
        assert 'pgid="9.0\\"\\nq\\\\"' in text
        _lint_exposition(text)
