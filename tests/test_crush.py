"""CRUSH differential tests: Python/JAX reimplementation vs the reference
C core compiled at test time (bit-exactness is the contract — BASELINE.md
correctness gate: batched mapping exhaustively equal to crush_do_rule).
"""

import numpy as np
import pytest

from ceph_tpu.crush import batched, hashing, ln, map as cmap_mod, mapper_ref
from ceph_tpu.crush.map import CrushMap, Rule, CRUSH_ITEM_NONE

from . import crush_oracle

ALG_UNIFORM, ALG_LIST, ALG_STRAW2 = 1, 2, 5
OP_CHOOSE_FIRSTN, OP_CHOOSE_INDEP = 2, 3
OP_CHOOSELEAF_FIRSTN, OP_CHOOSELEAF_INDEP = 6, 7
TUN_DEFAULT = [51, 0, 0, 1, 1, 1]  # total_tries+1 handled in C; see below


def lib_or_skip():
    lib = crush_oracle.get_oracle()
    if lib is None:
        pytest.skip("reference C oracle unavailable")
    return lib


def make_two_level(num_hosts, devs_per_host, dev_weights, leaf_alg="straw2"):
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1, "root": 2}
    host_ids = []
    host_weights = []
    for h in range(num_hosts):
        items = [h * devs_per_host + i for i in range(devs_per_host)]
        w = [int(dev_weights[i]) for i in items]
        hid = m.add_bucket(leaf_alg, 1, items, w, id=-2 - h)
        host_ids.append(hid)
        host_weights.append(sum(w))
    m.add_bucket("straw2", 2, host_ids, host_weights, id=-1, name="default")
    return m


def make_flat(ndev, dev_weights, leaf_alg="straw2"):
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1}
    m.add_bucket(leaf_alg, 1, list(range(ndev)),
                 [int(w) for w in dev_weights], id=-1, name="default")
    return m


def crush_tunables(m):
    t = m.tunables
    return [t.choose_total_tries, t.choose_local_tries,
            t.choose_local_fallback_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable]


def test_crush_ln_full_domain():
    lib = lib_or_skip()
    ref = np.array([lib.oracle_crush_ln(u) for u in range(0x10000)],
                   dtype=np.int64)
    assert np.array_equal(np.asarray(ln.crush_ln(np.arange(0x10000))), ref)


def test_crush_ln_jax_full_domain():
    lib = lib_or_skip()
    import jax
    import jax.numpy as jnp
    ref = np.array([lib.oracle_crush_ln(u) for u in range(0x10000)],
                   dtype=np.int64)
    with jax.enable_x64():
        out = jax.jit(lambda u: ln.crush_ln(u, xp=jnp))(jnp.arange(0x10000))
    assert np.array_equal(np.asarray(out), ref)


def test_rjenkins_hashes():
    lib = lib_or_skip()
    rng = np.random.default_rng(0)
    abc = rng.integers(0, 2**32, size=(300, 3), dtype=np.uint64).astype(
        np.uint32)
    with np.errstate(over="ignore"):
        m2 = np.asarray(hashing.hash32_2(abc[:, 0], abc[:, 1]))
        m3 = np.asarray(hashing.hash32_3(abc[:, 0], abc[:, 1], abc[:, 2]))
        m4 = np.asarray(hashing.hash32_4(abc[:, 0], abc[:, 1], abc[:, 2],
                                         abc[:, 0] ^ abc[:, 1]))
    for i, (a, b, c) in enumerate(abc):
        assert m2[i] == lib.oracle_hash32_2(int(a), int(b))
        assert m3[i] == lib.oracle_hash32_3(int(a), int(b), int(c))
        assert m4[i] == lib.oracle_hash32_4(int(a), int(b), int(c),
                                            int(a) ^ int(b))


@pytest.mark.parametrize("op,steps_op", [
    (OP_CHOOSE_INDEP, cmap_mod.RULE_CHOOSE_INDEP),
    (OP_CHOOSE_FIRSTN, cmap_mod.RULE_CHOOSE_FIRSTN),
])
def test_flat_bucket_vs_oracle(op, steps_op):
    lib = lib_or_skip()
    rng = np.random.default_rng(1)
    ndev = 12
    weights = rng.integers(1, 4 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[3] = 0            # marked out
    reweight[7] = 0x8000       # half reweighted
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1), (steps_op, 3, 0),
                           (cmap_mod.RULE_EMIT,)]))
    for x in range(60):
        ref = crush_oracle.oracle_map_run(
            lib, ALG_STRAW2, 1, ndev, weights, 1, op, 0, 3, x,
            reweight, crush_tunables(m), 3)
        mine = mapper_ref.crush_do_rule(m, 0, x, 3, list(reweight))
        assert mine == ref, (x, mine, ref)


@pytest.mark.parametrize("op,steps_op,leaf_alg,calg", [
    (OP_CHOOSELEAF_INDEP, cmap_mod.RULE_CHOOSELEAF_INDEP, "straw2", ALG_STRAW2),
    (OP_CHOOSELEAF_FIRSTN, cmap_mod.RULE_CHOOSELEAF_FIRSTN, "straw2", ALG_STRAW2),
    (OP_CHOOSELEAF_INDEP, cmap_mod.RULE_CHOOSELEAF_INDEP, "list", ALG_LIST),
    (OP_CHOOSELEAF_INDEP, cmap_mod.RULE_CHOOSELEAF_INDEP, "uniform", ALG_UNIFORM),
])
def test_two_level_chooseleaf_vs_oracle(op, steps_op, leaf_alg, calg):
    lib = lib_or_skip()
    rng = np.random.default_rng(2)
    hosts, per = 5, 4
    ndev = hosts * per
    if leaf_alg == "uniform":
        weights = np.full(ndev, 0x10000, dtype=np.uint32)
    else:
        weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[5] = 0
    reweight[11] = 0x4000
    m = make_two_level(hosts, per, weights, leaf_alg=leaf_alg)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1), (steps_op, 4, 1),
                           (cmap_mod.RULE_EMIT,)]))
    for x in range(40):
        ref = crush_oracle.oracle_map_run(
            lib, calg, hosts, per, weights, 0, op, 1, 4, x,
            reweight, crush_tunables(m), 4)
        mine = mapper_ref.crush_do_rule(m, 0, x, 4, list(reweight))
        assert mine == ref, (leaf_alg, x, mine, ref)


def test_legacy_tunables_vs_oracle():
    # pre-jewel tunables: local retries + fallback + vary_r=0 + stable=0
    lib = lib_or_skip()
    rng = np.random.default_rng(3)
    hosts, per = 4, 3
    ndev = hosts * per
    weights = rng.integers(1, 2 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[2] = 0
    m = make_two_level(hosts, per, weights)
    m.tunables = cmap_mod.Tunables(
        choose_total_tries=19, choose_local_tries=2,
        choose_local_fallback_tries=5, chooseleaf_descend_once=0,
        chooseleaf_vary_r=0, chooseleaf_stable=0)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_FIRSTN, 3, 1),
                           (cmap_mod.RULE_EMIT,)]))
    for x in range(40):
        ref = crush_oracle.oracle_map_run(
            lib, ALG_STRAW2, hosts, per, weights, 0,
            OP_CHOOSELEAF_FIRSTN, 1, 3, x, reweight, crush_tunables(m), 3)
        mine = mapper_ref.crush_do_rule(m, 0, x, 3, list(reweight))
        assert mine == ref, (x, mine, ref)


def test_batched_matches_ref_flat_indep():
    rng = np.random.default_rng(4)
    ndev = 10
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_INDEP, 4, 0),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[1] = 0
    reweight[8] = 0x9000
    xs = np.arange(300)
    got = batched.batched_do_rule(m, 0, xs, 4, reweight)
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 4, list(reweight))
        assert list(got[x]) == ref, (x, list(got[x]), ref)


def test_batched_matches_ref_two_level_chooseleaf_indep():
    # the EC placement shape: take root -> chooseleaf indep over hosts
    rng = np.random.default_rng(5)
    hosts, per = 6, 4
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = make_two_level(hosts, per, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_INDEP, 5, 1),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[0] = 0
    reweight[13] = 0x2000
    xs = np.arange(300)
    got = batched.batched_do_rule(m, 0, xs, 5, reweight)
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 5, list(reweight))
        assert list(got[x]) == ref, (x, list(got[x]), ref)


def test_batched_indep_holes_are_positional():
    # indep leaves CRUSH_ITEM_NONE holes rather than shifting (required by
    # EC shard positioning, ecbackend.rst:100-105)
    m = make_flat(4, [0x10000] * 4)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_INDEP, 4, 0),
                           (cmap_mod.RULE_EMIT,)]))
    # mark two devices fully out: only 2 of 4 slots can fill
    reweight = np.array([0x10000, 0, 0x10000, 0], dtype=np.int64)
    got = batched.batched_do_rule(m, 0, np.arange(50), 4, reweight)
    ref_holes = 0
    for row in got:
        for v in row:
            assert v in (0, 2, CRUSH_ITEM_NONE)
        ref_holes += sum(1 for v in row if v == CRUSH_ITEM_NONE)
    assert ref_holes == 50 * 2  # exactly the out devices leave holes


def test_create_rule_integration():
    # ErasureCode.create_rule analog: codec geometry drives rule creation
    from ceph_tpu import registry
    codec = registry.factory("jax_tpu", {"technique": "reed_sol_van",
                                         "k": "4", "m": "2", "w": "8"})
    m = make_two_level(8, 2, [0x10000] * 16)
    ruleno = m.add_simple_rule("ecpool", "default", "host", mode="indep",
                               rule_type=cmap_mod.POOL_TYPE_ERASURE)
    res = batched.batched_do_rule(m, ruleno, np.arange(20),
                                  codec.get_chunk_count())
    assert res.shape == (20, 6)
    for row in res:
        real = [v for v in row if v != CRUSH_ITEM_NONE]
        assert len(set(real)) == len(real)  # distinct devices


@pytest.mark.parametrize("op1,op2,pop1,pop2", [
    (OP_CHOOSE_FIRSTN, OP_CHOOSE_FIRSTN,
     cmap_mod.RULE_CHOOSE_FIRSTN, cmap_mod.RULE_CHOOSE_FIRSTN),
    (OP_CHOOSE_INDEP, OP_CHOOSE_INDEP,
     cmap_mod.RULE_CHOOSE_INDEP, cmap_mod.RULE_CHOOSE_INDEP),
])
def test_two_step_rule_vs_oracle(op1, op2, pop1, pop2):
    # multi-bucket working vector: choose N hosts, then 1 osd per host
    # (exercises the o+osize slice semantics of crush_do_rule:1019-1056)
    lib = lib_or_skip()
    rng = np.random.default_rng(7)
    hosts, per = 5, 3
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[4] = 0
    m = make_two_level(hosts, per, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1), (pop1, 3, 1),
                           (pop2, 1, 0), (cmap_mod.RULE_EMIT,)]))
    for x in range(40):
        ref = crush_oracle.oracle_map_run(
            lib, ALG_STRAW2, hosts, per, weights, 0, op1, 1, 3, x,
            reweight, crush_tunables(m), 3, rule_op2=op2, choose_type2=0,
            numrep2=1)
        mine = mapper_ref.crush_do_rule(m, 0, x, 3, list(reweight))
        assert mine == ref, (x, mine, ref)


def test_numrep_exceeds_result_max_vs_oracle():
    # C keeps the rule numrep as the retry stride even when result_max
    # truncates the output count (mapper.c:1039-1046)
    lib = lib_or_skip()
    rng = np.random.default_rng(8)
    ndev = 10
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[2] = 0
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_INDEP, 6, 0),
                           (cmap_mod.RULE_EMIT,)]))
    for x in range(40):
        ref = crush_oracle.oracle_map_run(
            lib, ALG_STRAW2, 1, ndev, weights, 1, OP_CHOOSE_INDEP, 0, 6, x,
            reweight, crush_tunables(m), 4)
        mine = mapper_ref.crush_do_rule(m, 0, x, 4, list(reweight))
        assert mine == ref, (x, mine, ref)
    # batched fast path agrees too
    got = batched.batched_do_rule(m, 0, np.arange(40), 4,
                                  np.asarray(reweight, dtype=np.int64))
    for x in range(40):
        ref = mapper_ref.crush_do_rule(m, 0, x, 4, list(reweight))
        assert list(got[x]) == ref, (x, list(got[x]), ref)


def test_batched_device_at_root_level_permanent_none():
    # a device directly under the root alongside host buckets: chooseleaf
    # over hosts must mark reps landing on the device as permanent NONE
    # (mapper.c:744-751), in both the interpreter and the batched kernel
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1, "root": 2}
    m.add_bucket("straw2", 1, [0, 1], [0x10000, 0x10000], id=-2)
    m.add_bucket("straw2", 1, [2, 3], [0x10000, 0x10000], id=-3)
    # root holds two hosts AND a bare device 4
    m.add_bucket("straw2", 2, [-2, -3, 4], [0x20000, 0x20000, 0x10000],
                 id=-1, name="default")
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_INDEP, 3, 1),
                           (cmap_mod.RULE_EMIT,)]))
    xs = np.arange(200)
    got = batched.batched_do_rule(m, 0, xs, 3)
    saw_hole = False
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 3)
        assert list(got[x]) == ref, (x, list(got[x]), ref)
        saw_hole = saw_hole or CRUSH_ITEM_NONE in ref
    assert saw_hole  # the bare device must have produced permanent holes


def test_batched_malformed_map_falls_back():
    # dangling bucket reference: batched path must degrade like the
    # scalar interpreter (holes), not crash
    m = CrushMap()
    m.type_names = {"osd": 0, "host": 1, "root": 2}
    m.add_bucket("straw2", 1, [0, 1], [0x10000] * 2, id=-2)
    m.add_bucket("straw2", 2, [-2, -9], [0x20000, 0x20000], id=-1,
                 name="default")
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_INDEP, 2, 1),
                           (cmap_mod.RULE_EMIT,)]))
    got = batched.batched_do_rule(m, 0, np.arange(20), 2)
    for x in range(20):
        ref = mapper_ref.crush_do_rule(m, 0, x, 2)
        assert list(got[x]) == ref


def test_batched_matches_ref_flat_firstn():
    # the replicated-pool shape: choose firstn over devices
    rng = np.random.default_rng(6)
    ndev = 10
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 3, 0),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[2] = 0
    reweight[7] = 0x8000
    xs = np.arange(300)
    got = batched.batched_do_rule(m, 0, xs, 3, reweight)
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 3, list(reweight))
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref, (x, mine, ref)


def test_batched_matches_ref_two_level_chooseleaf_firstn():
    # the canonical replicated rule: take root -> chooseleaf firstn
    # over hosts -> emit (CrushWrapper::add_simple_rule default)
    rng = np.random.default_rng(7)
    hosts, per = 6, 4
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    m = make_two_level(hosts, per, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_FIRSTN, 3, 1),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[5] = 0
    reweight[16] = 0x4000
    xs = np.arange(300)
    got = batched.batched_do_rule(m, 0, xs, 3, reweight)
    for x in xs:
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 3, list(reweight))
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref, (x, mine, ref)


def test_batched_firstn_compacts_not_holes():
    # firstn output shifts out devices away (can_shift_osds), unlike
    # indep's positional holes
    m = make_flat(4, [0x10000] * 4)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 4, 0),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.array([0x10000, 0, 0x10000, 0], dtype=np.int64)
    got = batched.batched_do_rule(m, 0, np.arange(50), 4, reweight)
    for x in range(50):
        ref = mapper_ref.crush_do_rule(m, 0, x, 4, list(reweight))
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref
        # holes only at the tail (compacted prefix)
        row = list(got[x])
        assert row[:len(mine)] == mine


def test_batched_firstn_numrep_exceeds_available():
    m = make_two_level(3, 2, [0x10000] * 6)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_FIRSTN, 5, 1),
                           (cmap_mod.RULE_EMIT,)]))
    got = batched.batched_do_rule(m, 0, np.arange(100), 5)
    for x in range(100):
        ref = mapper_ref.crush_do_rule(m, 0, x, 5, None)
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref, (x, mine, ref)


def test_batched_firstn_exotic_tunables_fall_back():
    # non-jewel local retries ride the scalar interpreter
    m = make_flat(6, [0x10000] * 6)
    m.tunables.choose_local_tries = 2
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 3, 0),
                           (cmap_mod.RULE_EMIT,)]))
    got = batched.batched_do_rule(m, 0, np.arange(30), 3)
    for x in range(30):
        ref = mapper_ref.crush_do_rule(m, 0, x, 3, None)
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref


def test_batched_firstn_bucket_target_ignores_device_reweight():
    # choose firstn emitting BUCKETS: is_out applies to devices only
    # (mapper.c:581-585); reweight must not reject host buckets
    m = make_two_level(4, 2, [0x10000] * 8)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 2, 1),
                           (cmap_mod.RULE_EMIT,)]))
    reweight = np.full(8, 0x10000, dtype=np.int64)
    reweight[0] = 0
    got = batched.batched_do_rule(m, 0, np.arange(20), 2, reweight)
    for x in range(20):
        ref = mapper_ref.crush_do_rule(m, 0, x, 2, list(reweight))
        mine = [int(v) for v in got[x] if v != CRUSH_ITEM_NONE]
        assert mine == ref, (x, mine, ref)


# -- choose_args (weight-sets / ids) differential tests ------------------

@pytest.mark.parametrize("op,steps_op,positions,with_ids", [
    (OP_CHOOSE_INDEP, cmap_mod.RULE_CHOOSE_INDEP, 1, False),
    (OP_CHOOSE_INDEP, cmap_mod.RULE_CHOOSE_INDEP, 3, True),
    (OP_CHOOSE_FIRSTN, cmap_mod.RULE_CHOOSE_FIRSTN, 1, True),
    (OP_CHOOSE_FIRSTN, cmap_mod.RULE_CHOOSE_FIRSTN, 3, False),
])
def test_choose_args_flat_vs_oracle(op, steps_op, positions, with_ids):
    """Weight-set + ids substitution in a flat straw2 bucket must be
    bit-equal to the reference's bucket_straw2_choose with
    crush_choose_arg (mapper.c:302-341, 459-512)."""
    lib = lib_or_skip()
    rng = np.random.default_rng(21)
    ndev = 10
    weights = rng.integers(1, 4 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[2] = 0x8000
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1), (steps_op, 3, 0),
                           (cmap_mod.RULE_EMIT,)]))
    ws = rng.integers(0, 5 * 0x10000, size=(positions, ndev),
                      dtype=np.uint32)
    ws[:, 0] = 0x10000  # keep at least one nonzero weight everywhere
    ids = (rng.permutation(ndev).astype(np.int32) + 100) if with_ids \
        else None
    cargs = {-1: {"weight_set": [[int(w) for w in row] for row in ws],
                  "ids": [int(i) for i in ids] if ids is not None
                  else None}}
    mask = [1 | (2 if with_ids else 0)]
    ws_flat = ws.reshape(-1)
    ids_flat = ids if ids is not None else np.zeros(0, dtype=np.int32)
    for x in range(80):
        ref = crush_oracle.oracle_map_run_cargs(
            lib, ALG_STRAW2, 1, ndev, weights, 1, op, 0, 3, x,
            reweight, crush_tunables(m), 3,
            positions, mask, ws_flat, ids_flat)
        mine = mapper_ref.crush_do_rule(m, 0, x, 3, list(reweight),
                                        choose_args=cargs)
        assert mine == ref, (x, mine, ref)


@pytest.mark.parametrize("op,steps_op", [
    (OP_CHOOSELEAF_INDEP, cmap_mod.RULE_CHOOSELEAF_INDEP),
    (OP_CHOOSELEAF_FIRSTN, cmap_mod.RULE_CHOOSELEAF_FIRSTN),
])
def test_choose_args_two_level_chooseleaf_vs_oracle(op, steps_op):
    """Weight-sets on BOTH the root and the host buckets through a
    chooseleaf descent (positions > 1 exercises the per-outpos weight
    selection and its clamp)."""
    lib = lib_or_skip()
    rng = np.random.default_rng(22)
    hosts, per, positions = 5, 4, 2
    ndev = hosts * per
    weights = rng.integers(1, 3 * 0x10000, size=ndev, dtype=np.uint32)
    reweight = np.full(ndev, 0x10000, dtype=np.uint32)
    reweight[7] = 0
    m = make_two_level(hosts, per, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1), (steps_op, 4, 1),
                           (cmap_mod.RULE_EMIT,)]))
    # root weight-set (over hosts) + per-host weight-sets (over devs)
    root_ws = rng.integers(0x8000, 4 * 0x10000, size=(positions, hosts),
                           dtype=np.uint32)
    host_ws = [rng.integers(0x4000, 3 * 0x10000, size=(positions, per),
                            dtype=np.uint32) for _ in range(hosts)]
    cargs = {-1: {"weight_set": [[int(w) for w in row]
                                 for row in root_ws], "ids": None}}
    for h in range(hosts):
        cargs[-2 - h] = {"weight_set": [[int(w) for w in row]
                                        for row in host_ws[h]],
                         "ids": None}
    mask = [1] * (1 + hosts)
    ws_flat = np.concatenate([root_ws.reshape(-1)]
                             + [hw.reshape(-1) for hw in host_ws])
    ids_flat = np.zeros(0, dtype=np.int32)
    for x in range(50):
        ref = crush_oracle.oracle_map_run_cargs(
            lib, ALG_STRAW2, hosts, per, weights, 0, op, 1, 4, x,
            reweight, crush_tunables(m), 4,
            positions, mask, ws_flat, ids_flat)
        mine = mapper_ref.crush_do_rule(m, 0, x, 4, list(reweight),
                                        choose_args=cargs)
        assert mine == ref, (x, mine, ref)


def test_choose_args_balancer_remap_without_base_weights():
    """The balancer contract: adjusting a weight-set copy remaps PGs
    while the bucket's base weights are untouched, and dropping the
    weight-set restores the original mapping (CrushWrapper
    create_choose_args / choose_args_adjust_item_weight roles)."""
    rng = np.random.default_rng(23)
    ndev = 8
    weights = rng.integers(0x10000, 3 * 0x10000, size=ndev,
                           dtype=np.uint32)
    m = make_flat(ndev, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 3, 0),
                           (cmap_mod.RULE_EMIT,)]))
    base_weights = m.buckets[-1].weights.copy()
    before = [mapper_ref.crush_do_rule(m, 0, x, 3) for x in range(100)]
    m.create_choose_args(cmap_mod.DEFAULT_CHOOSE_ARGS, positions=1)
    # nudge one overloaded device down hard in the weight-set copy
    m.choose_args_adjust_item_weight(cmap_mod.DEFAULT_CHOOSE_ARGS,
                                     -1, 0, 0x1000)
    after = [mapper_ref.crush_do_rule(
        m, 0, x, 3, choose_args=cmap_mod.DEFAULT_CHOOSE_ARGS)
        for x in range(100)]
    assert np.array_equal(m.buckets[-1].weights, base_weights)
    assert before != after          # the weight-set change remapped
    moved = sum(1 for b, a in zip(before, after) if b != a)
    assert moved > 0
    # osd 0 loses load under the new weight-set
    cnt_before = sum(r.count(0) for r in before)
    cnt_after = sum(r.count(0) for r in after)
    assert cnt_after < cnt_before
    # dropping the set restores the base mapping
    m.choose_args.clear()
    restored = [mapper_ref.crush_do_rule(
        m, 0, x, 3, choose_args=cmap_mod.DEFAULT_CHOOSE_ARGS)
        for x in range(100)]
    assert restored == before


@pytest.mark.parametrize("steps_op,positions,with_ids", [
    (cmap_mod.RULE_CHOOSE_INDEP, 1, True),
    (cmap_mod.RULE_CHOOSE_FIRSTN, 1, False),
    (cmap_mod.RULE_CHOOSE_FIRSTN, 3, True),
    (cmap_mod.RULE_CHOOSELEAF_INDEP, 2, False),
    (cmap_mod.RULE_CHOOSELEAF_FIRSTN, 2, False),
])
def test_batched_choose_args_matches_scalar(steps_op, positions,
                                            with_ids):
    """The device kernels' choose_args path (hash-id substitution,
    per-position weight-set tensor, live-outpos selection in firstn)
    must be bit-equal to the scalar interpreter — which is itself
    oracle-verified above."""
    rng = np.random.default_rng(31)
    chooseleaf = steps_op in (cmap_mod.RULE_CHOOSELEAF_INDEP,
                              cmap_mod.RULE_CHOOSELEAF_FIRSTN)
    if chooseleaf:
        hosts, per = 5, 3
        ndev = hosts * per
        weights = rng.integers(0x8000, 3 * 0x10000, size=ndev,
                               dtype=np.uint32)
        m = make_two_level(hosts, per, weights)
        buckets = [-1] + [-2 - h for h in range(hosts)]
        ctype = 1
    else:
        ndev = 9
        weights = rng.integers(0x8000, 3 * 0x10000, size=ndev,
                               dtype=np.uint32)
        m = make_flat(ndev, weights)
        buckets = [-1]
        ctype = 0
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (steps_op, 3, ctype),
                           (cmap_mod.RULE_EMIT,)]))
    cargs = {}
    for bid in buckets:
        bsz = m.buckets[bid].size
        ws = rng.integers(0x2000, 4 * 0x10000, size=(positions, bsz))
        ids = ([int(i) + 50 for i in
                rng.permutation(bsz)] if with_ids and bid == -1
               else None)
        cargs[bid] = {"weight_set": [[int(w) for w in row]
                                     for row in ws], "ids": ids}
    reweight = np.full(ndev, 0x10000, dtype=np.int64)
    reweight[1] = 0x9000
    xs = np.arange(120)
    got = batched.batched_do_rule(m, 0, xs, 3, list(reweight),
                                  choose_args=cargs)
    for i, x in enumerate(xs):
        ref = mapper_ref.crush_do_rule(m, 0, int(x), 3, list(reweight),
                                       choose_args=cargs)
        mine = [v for v in got[i] if v != CRUSH_ITEM_NONE] \
            if steps_op in (cmap_mod.RULE_CHOOSE_FIRSTN,
                            cmap_mod.RULE_CHOOSELEAF_FIRSTN) else list(got[i])
        if steps_op in (cmap_mod.RULE_CHOOSE_INDEP,
                        cmap_mod.RULE_CHOOSELEAF_INDEP):
            ref = ref + [CRUSH_ITEM_NONE] * (3 - len(ref))
        assert mine == ref, (x, list(got[i]), ref)


def test_choose_args_adjust_propagates_to_ancestors():
    """choose_args_adjust_item_weight writes every position and
    propagates the bucket's per-position totals into ancestor
    weight-sets (CrushWrapper::choose_args_adjust_item_weightf walks
    the parents) — draining a device must shed load at the ROOT draw
    too, not just inside its host."""
    rng = np.random.default_rng(61)
    hosts, per = 3, 2
    weights = np.full(hosts * per, 0x10000, dtype=np.uint32)
    m = make_two_level(hosts, per, weights)
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSELEAF_FIRSTN, 2, 1),
                           (cmap_mod.RULE_EMIT,)]))
    m.create_choose_args(0, positions=2)
    m.choose_args_adjust_item_weight(0, -2, 0, 0)   # drain osd.0
    arg_host = m.choose_args[0][-2]
    assert all(row[0] == 0 for row in arg_host["weight_set"])   # all positions
    arg_root = m.choose_args[0][-1]
    # host0's total dropped to per-1 devices' worth in the root's set
    assert all(row[0] == 0x10000 for row in arg_root["weight_set"])
    assert all(row[1] == 2 * 0x10000 for row in arg_root["weight_set"])


def test_choose_args_bad_sizes_rejected():
    rng = np.random.default_rng(62)
    m = make_flat(4, np.full(4, 0x10000, dtype=np.uint32))
    m.add_rule(Rule(steps=[(cmap_mod.RULE_TAKE, -1),
                           (cmap_mod.RULE_CHOOSE_FIRSTN, 2, 0),
                           (cmap_mod.RULE_EMIT,)]))
    with pytest.raises(ValueError):
        mapper_ref.crush_do_rule(m, 0, 1, 2, choose_args={
            -1: {"ids": None, "weight_set": [[1, 2]]}})
    with pytest.raises(ValueError):
        mapper_ref.crush_do_rule(m, 0, 1, 2, choose_args={
            -1: {"ids": [9, 9], "weight_set": None}})
    # None entries are legal everywhere
    assert mapper_ref.crush_do_rule(m, 0, 1, 2,
                                    choose_args={-1: None})
    from ceph_tpu import native
    try:
        native.lib()
    except Exception:
        pytest.skip("native lib unavailable")
    assert native.crush_do_rule_native(m, 0, 1, 2,
                                       choose_args={-1: None})
